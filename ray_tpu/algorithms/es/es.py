"""Evolution Strategies (ES) and Augmented Random Search (ARS).

Counterpart of the reference's ``rllib/algorithms/es/es.py`` (Salimans
et al. 2017: antithetic Gaussian perturbations, centered-rank weighting,
shared noise table, Adam on the flat parameter vector) and
``rllib/algorithms/ars/ars.py`` (Mania et al. 2018: top-k direction
selection, reward-std scaling, plain SGD).

These are the showcase for the task/actor API: perturbation rollouts are
embarrassingly parallel `@ray.remote` actors, each holding an env + the
policy network + a deterministically re-derived slice view of the shared
noise table (the reference ships a 250M-float table through the object
store — re-seeding locally is free and exact). The learner-side update
(gather noise rows, centered-rank weighted sum, Adam) is host numpy: the
parameter vectors are tiny MLPs, far below MXU-worthwhile sizes."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

import ray_tpu as ray
from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.policy.jax_policy import JaxPolicy
from ray_tpu.utils.filter import get_filter


class SharedNoiseTable:
    """Deterministic Gaussian noise table (reference es.py
    SharedNoiseTable / create_shared_noise). Every process re-derives
    the identical table from the seed instead of shipping ~1GB."""

    def __init__(self, count: int = 25_000_000, seed: int = 42):
        self.noise = np.random.RandomState(seed).randn(count).astype(
            np.float32
        )

    def get(self, i: int, dim: int) -> np.ndarray:
        return self.noise[i : i + dim]

    def sample_index(self, rng: np.random.RandomState, dim: int) -> int:
        return int(rng.randint(0, len(self.noise) - dim + 1))


def compute_centered_ranks(x: np.ndarray) -> np.ndarray:
    """reference es_utils.py compute_centered_ranks: ranks scaled to
    [-0.5, 0.5]."""
    flat = x.ravel()
    ranks = np.empty(flat.size, dtype=np.float32)
    ranks[flat.argsort()] = np.arange(flat.size, dtype=np.float32)
    ranks = ranks.reshape(x.shape)
    return ranks / (x.size - 1) - 0.5


class ESConfig(AlgorithmConfig):
    """reference es.py ESConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or ES)
        self.num_workers = 2
        self.episodes_per_batch = 40
        self.train_batch_size = 2000  # min timesteps per iteration
        self.noise_stdev = 0.02
        self.stepsize = 0.01
        self.l2_coeff = 0.005
        self.eval_prob = 0.03
        self.noise_size = 25_000_000
        self.report_length = 10
        self.observation_filter = "MeanStdFilter"
        self.model = {"fcnet_hiddens": [64, 64], "fcnet_activation": "tanh"}

    def training(
        self,
        *,
        episodes_per_batch: Optional[int] = None,
        noise_stdev: Optional[float] = None,
        stepsize: Optional[float] = None,
        l2_coeff: Optional[float] = None,
        eval_prob: Optional[float] = None,
        noise_size: Optional[int] = None,
        **kwargs,
    ) -> "ESConfig":
        super().training(**kwargs)
        if episodes_per_batch is not None:
            self.episodes_per_batch = episodes_per_batch
        if noise_stdev is not None:
            self.noise_stdev = noise_stdev
        if stepsize is not None:
            self.stepsize = stepsize
        if l2_coeff is not None:
            self.l2_coeff = l2_coeff
        if eval_prob is not None:
            self.eval_prob = eval_prob
        if noise_size is not None:
            self.noise_size = noise_size
        return self


class ARSConfig(ESConfig):
    """reference ars.py ARSConfig."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or ARS)
        self.num_rollouts = 32  # directions per iteration
        self.rollouts_used = 32  # top-k directions kept
        self.sgd_stepsize = 0.01
        self.noise_stdev = 0.02
        self.eval_prob = 0.0

    def training(
        self,
        *,
        num_rollouts: Optional[int] = None,
        rollouts_used: Optional[int] = None,
        sgd_stepsize: Optional[float] = None,
        **kwargs,
    ) -> "ARSConfig":
        super().training(**kwargs)
        if num_rollouts is not None:
            self.num_rollouts = num_rollouts
        if rollouts_used is not None:
            self.rollouts_used = rollouts_used
        if sgd_stepsize is not None:
            self.sgd_stepsize = sgd_stepsize
        return self


class ESJaxPolicy(JaxPolicy):
    """The evaluated policy: deterministic forward of the catalog model.
    ES never calls loss/learn — weights move via flat-vector updates."""

    def loss(self, params, batch, rng, coeffs):
        raise NotImplementedError("ES updates parameters via evolution")

    def get_flat_weights(self) -> np.ndarray:
        from jax.flatten_util import ravel_pytree

        flat, unravel = ravel_pytree(jax.device_get(self.params))
        self._unravel = unravel
        return np.asarray(flat, np.float32)

    def set_flat_weights(self, flat: np.ndarray) -> None:
        if not hasattr(self, "_unravel"):
            self.get_flat_weights()
        self.set_weights(self._unravel(np.asarray(flat, np.float32)))


class _RolloutEngine:
    """Env + model + filter, shared by perturbation workers and the
    driver-side evaluation path."""

    def __init__(self, config: Dict, env_spec):
        import gymnasium as gym

        from ray_tpu.env.registry import get_env_creator
        from ray_tpu.models.catalog import ModelCatalog

        creator = get_env_creator(env_spec)
        self.env = creator(config.get("env_config") or {})
        model_config = dict(config.get("model") or {})
        self.dist_class, num_outputs = ModelCatalog.get_action_dist(
            self.env.action_space, model_config, config.get("dist_type")
        )
        self.model = ModelCatalog.get_model(
            self.env.observation_space,
            self.env.action_space,
            num_outputs,
            model_config,
        )
        rng = jax.random.PRNGKey(int(config.get("seed") or 0))
        dummy = np.zeros(
            (2,) + self.env.observation_space.shape, np.float32
        )
        params = self.model.init(rng, dummy)
        from jax.flatten_util import ravel_pytree

        flat, self._unravel = ravel_pytree(params)
        self.num_params = int(flat.size)
        self.filter = get_filter(
            config.get("observation_filter", "MeanStdFilter"),
            self.env.observation_space.shape,
        )

        def act(params, obs):
            dist_inputs, _, _ = self.model.apply(params, obs[None])
            return self.dist_class(dist_inputs).deterministic_sample()[0]

        self._act = jax.jit(act)

    def rollout(
        self, flat_params: np.ndarray, update_filter: bool = True
    ) -> Tuple[float, int]:
        params = self._unravel(np.asarray(flat_params, np.float32))
        obs, _ = self.env.reset()
        total, steps = 0.0, 0
        done = False
        while not done:
            fobs = self.filter(
                np.asarray(obs, np.float32), update=update_filter
            )
            action = np.asarray(self._act(params, fobs))
            obs, reward, terminated, truncated, _ = self.env.step(action)
            total += float(reward)
            steps += 1
            done = terminated or truncated
        return total, steps


@ray.remote
class _ESWorker:
    """Perturbation-rollout actor (reference es.py Worker)."""

    def __init__(self, config: Dict, env_spec, worker_seed: int):
        self.config = dict(config)
        self.engine = _RolloutEngine(self.config, env_spec)
        self.noise = SharedNoiseTable(
            int(config.get("noise_size", 25_000_000))
        )
        self.rng = np.random.RandomState(worker_seed)
        self.stdev = float(config.get("noise_stdev", 0.02))
        self.eval_prob = float(config.get("eval_prob", 0.0))

    def do_rollouts(
        self, flat_params: np.ndarray, filter_state, num_pairs: int
    ) -> Dict:
        if filter_state is not None:
            self.engine.filter.sync(filter_state)
        self.engine.filter.clear_buffer()
        flat_params = np.asarray(flat_params, np.float32)
        dim = flat_params.size
        indices: List[int] = []
        pos_returns: List[float] = []
        neg_returns: List[float] = []
        eval_returns: List[float] = []
        steps = 0
        lengths: List[int] = []
        for _ in range(num_pairs):
            if self.eval_prob and self.rng.rand() < self.eval_prob:
                ret, n = self.engine.rollout(
                    flat_params, update_filter=False
                )
                eval_returns.append(ret)
                steps += n
                continue
            idx = self.noise.sample_index(self.rng, dim)
            pert = self.stdev * self.noise.get(idx, dim)
            r_pos, n_pos = self.engine.rollout(flat_params + pert)
            r_neg, n_neg = self.engine.rollout(flat_params - pert)
            indices.append(idx)
            pos_returns.append(r_pos)
            neg_returns.append(r_neg)
            lengths += [n_pos, n_neg]
            steps += n_pos + n_neg
        return {
            "indices": indices,
            "pos_returns": pos_returns,
            "neg_returns": neg_returns,
            "lengths": lengths,
            "eval_returns": eval_returns,
            "steps": steps,
            "filter_buffer": self.engine.filter.as_serializable(),
        }


class _FlatAdam:
    """Adam on the flat parameter vector (reference
    es/optimizers.py Adam)."""

    def __init__(self, dim: int, stepsize: float):
        self.m = np.zeros(dim, np.float32)
        self.v = np.zeros(dim, np.float32)
        self.t = 0
        self.stepsize = stepsize
        self.beta1, self.beta2, self.eps = 0.9, 0.999, 1e-8

    def update(self, theta: np.ndarray, grad: np.ndarray) -> np.ndarray:
        self.t += 1
        self.m = self.beta1 * self.m + (1 - self.beta1) * grad
        self.v = self.beta2 * self.v + (1 - self.beta2) * grad * grad
        a = (
            self.stepsize
            * np.sqrt(1 - self.beta2**self.t)
            / (1 - self.beta1**self.t)
        )
        return theta - a * self.m / (np.sqrt(self.v) + self.eps)


class ES(Algorithm):
    _default_policy_class = ESJaxPolicy

    @classmethod
    def get_default_config(cls) -> ESConfig:
        return ESConfig(cls)

    def setup(self, config: Dict) -> None:
        # The standard WorkerSet serves evaluation/checkpointing only;
        # perturbation rollouts run on dedicated ES actors.
        self._es_num_workers = max(1, int(config.get("num_workers", 2)))
        config = dict(config, num_workers=0)
        super().setup(config)
        policy = self.get_policy()
        self._theta = policy.get_flat_weights()
        self.noise = SharedNoiseTable(
            int(config.get("noise_size", 25_000_000))
        )
        self._filter = get_filter(
            config.get("observation_filter", "MeanStdFilter"),
            policy.observation_space.shape,
        )
        self._optimizer = _FlatAdam(
            self._theta.size, float(config.get("stepsize", 0.01))
        )
        seed = int(config.get("seed") or 0)
        # Strip driver-only runtime objects (the jax Mesh in "_mesh")
        # before shipping the config to worker processes.
        worker_config = {
            k: v for k, v in config.items() if not k.startswith("_")
        }
        self._es_workers = [
            _ESWorker.remote(
                worker_config, config.get("env"), seed * 1000 + i
            )
            for i in range(self._es_num_workers)
        ]
        self._eval_returns: List[float] = []

    def _pairs_per_iteration(self) -> int:
        return max(
            1, int(self.config.get("episodes_per_batch", 40)) // 2
        )

    def _collect(self, num_pairs_total: int) -> List[Dict]:
        per = -(-num_pairs_total // len(self._es_workers))
        filter_state = self._filter.as_serializable()
        refs = [
            w.do_rollouts.remote(self._theta, filter_state, per)
            for w in self._es_workers
        ]
        return ray.get(refs)

    def _gather_iteration(self) -> Dict:
        """Collect perturbation rollouts until BOTH the episode floor
        (episodes_per_batch) and the timestep floor (train_batch_size)
        are met — reference es.py _collect_results loops on exactly
        these two minima. Merges worker results, filter deltas, and
        episode metrics."""
        from ray_tpu.evaluation.metrics import RolloutMetrics

        pairs_target = self._pairs_per_iteration()
        min_steps = int(self.config.get("train_batch_size", 0) or 0)
        agg = {
            "indices": [],
            "pos": [],
            "neg": [],
            "steps": 0,
        }
        self._eval_returns = []
        while True:
            remaining = max(1, pairs_target - len(agg["indices"]))
            for r in self._collect(remaining):
                agg["indices"] += list(r["indices"])
                agg["pos"] += list(r["pos_returns"])
                agg["neg"] += list(r["neg_returns"])
                agg["steps"] += r["steps"]
                self._eval_returns += list(r["eval_returns"])
                self._filter.apply_changes(
                    r["filter_buffer"], with_buffer=False
                )
                lens = list(r.get("lengths", []))
                rets = list(r["pos_returns"]) + list(r["neg_returns"])
                lens = lens[0::2] + lens[1::2]  # pos-then-neg order
                lens += [0] * (len(rets) - len(lens))
                for ret, ln in zip(rets, lens):
                    self._episode_history.append(
                        RolloutMetrics(ln, ret)
                    )
            if (
                len(agg["indices"]) >= pairs_target
                and agg["steps"] >= min_steps
            ):
                break
        self._counters[NUM_ENV_STEPS_SAMPLED] += agg["steps"]
        self._counters[NUM_AGENT_STEPS_SAMPLED] += agg["steps"]
        # Keep the learned normalization visible outside the ES rollout
        # path: checkpoints and evaluation read the local worker's
        # filters (reference es.py syncs policy.observation_filter).
        lw = self.workers.local_worker()
        if lw is not None and hasattr(lw, "filters"):
            for f in lw.filters.values():
                f.sync(self._filter.as_serializable())
        return agg

    def _apply_results(self, agg: Dict) -> Dict:
        """Centered-rank weighted noise update (reference es.py step)."""
        cfg = self.config
        stdev = float(cfg.get("noise_stdev", 0.02))
        indices, pos, neg = agg["indices"], agg["pos"], agg["neg"]
        if not indices:
            return {"episodes_this_iter": 0}
        returns = np.stack(
            [np.asarray(pos, np.float32), np.asarray(neg, np.float32)],
            axis=1,
        )  # (P, 2)
        ranks = compute_centered_ranks(returns)
        weights = ranks[:, 0] - ranks[:, 1]  # (P,)
        dim = self._theta.size
        rows = np.stack([self.noise.get(i, dim) for i in indices])
        grad = weights @ rows / (len(indices) * stdev)
        # gradient ASCENT with L2 decay toward 0 (reference es.py:~320)
        update = -grad + float(cfg.get("l2_coeff", 0.005)) * self._theta
        self._theta = self._optimizer.update(self._theta, update)
        self.get_policy().set_flat_weights(self._theta)
        return {
            "episodes_this_iter": 2 * len(indices),
            "weights_norm": float(np.linalg.norm(self._theta)),
            "grad_norm": float(np.linalg.norm(grad)),
            "update_ratio": float(
                np.linalg.norm(self._optimizer.m)
                / (np.linalg.norm(self._theta) + 1e-8)
            ),
            "noise_std": stdev,
            "mean_pos_return": float(np.mean(pos)),
            "mean_neg_return": float(np.mean(neg)),
            "episode_reward_mean_perturbed": float(np.mean(returns)),
        }

    def training_step(self) -> Dict:
        agg = self._gather_iteration()
        info = self._apply_results(agg)
        if self._eval_returns:
            info["eval_reward_mean"] = float(
                np.mean(self._eval_returns)
            )
        return info

    # -- checkpointing ---------------------------------------------------

    def __getstate__(self) -> Dict:
        state = super().__getstate__()
        state["es"] = {
            "theta": self._theta,
            "optimizer": self._optimizer.__dict__,
            "filter": self._filter.as_serializable(),
        }
        return state

    def __setstate__(self, state: Dict) -> None:
        super().__setstate__(state)
        es = state.get("es")
        if es:
            self._theta = np.asarray(es["theta"], np.float32)
            self._optimizer.__dict__.update(es["optimizer"])
            self._filter.sync(es["filter"])
            self.get_policy().set_flat_weights(self._theta)

    def cleanup(self) -> None:
        for w in getattr(self, "_es_workers", []):
            try:
                ray.kill(w)
            except Exception:
                pass
        super().cleanup()


class ARS(ES):
    """reference ars.py: top-k direction selection + reward-std scaling
    + plain SGD instead of Adam."""

    @classmethod
    def get_default_config(cls) -> ARSConfig:
        return ARSConfig(cls)

    def _pairs_per_iteration(self) -> int:
        return max(1, int(self.config.get("num_rollouts", 32)))

    def _apply_results(self, agg: Dict) -> Dict:
        cfg = self.config
        stdev = float(cfg.get("noise_stdev", 0.02))
        indices, pos, neg = agg["indices"], agg["pos"], agg["neg"]
        if not indices:
            return {"episodes_this_iter": 0}
        pos_a = np.asarray(pos, np.float32)
        neg_a = np.asarray(neg, np.float32)
        # top-k directions by max(pos, neg) (Mania et al. alg. 2)
        k = min(
            int(cfg.get("rollouts_used", len(indices))), len(indices)
        )
        order = np.argsort(-np.maximum(pos_a, neg_a))[:k]
        used_rewards = np.concatenate([pos_a[order], neg_a[order]])
        reward_std = max(float(used_rewards.std()), 1e-6)
        dim = self._theta.size
        rows = np.stack([self.noise.get(indices[i], dim) for i in order])
        grad = (pos_a[order] - neg_a[order]) @ rows / (k * reward_std)
        step_size = float(cfg.get("sgd_stepsize", 0.01))
        self._theta = self._theta + step_size * grad
        self.get_policy().set_flat_weights(self._theta)
        return {
            "episodes_this_iter": 2 * len(indices),
            "weights_norm": float(np.linalg.norm(self._theta)),
            "grad_norm": float(np.linalg.norm(grad)),
            "reward_std": reward_std,
            "noise_std": stdev,
            "mean_pos_return": float(np.mean(pos_a)),
            "mean_neg_return": float(np.mean(neg_a)),
            "episode_reward_mean_perturbed": float(
                np.mean(np.stack([pos_a, neg_a]))
            ),
        }
