from ray_tpu.algorithms.es.es import ARS, ARSConfig, ES, ESConfig

__all__ = ["ES", "ESConfig", "ARS", "ARSConfig"]
