from ray_tpu.algorithms.a2c.a2c import A2C, A2CConfig, A2CJaxPolicy, A3C, A3CConfig

__all__ = ["A2C", "A2CConfig", "A2CJaxPolicy", "A3C", "A3CConfig"]
