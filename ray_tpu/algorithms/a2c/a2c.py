"""A2C (sync) + A3C-style async gradients.

Counterpart of the reference's ``rllib/algorithms/a2c/a2c.py`` and
``a3c/a3c.py:191`` (async grads: workers compute gradients, driver
applies). A2C here is the synchronous path: sample → single-pass
actor-critic loss on the learner mesh. The A3C flavor reuses the
compute_gradients/apply_gradients JaxPolicy parity API.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

import ray_tpu as ray
from ray_tpu.algorithms.algorithm import (
    Algorithm,
    NUM_AGENT_STEPS_SAMPLED,
    NUM_ENV_STEPS_SAMPLED,
)
from ray_tpu.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID, SampleBatch
from ray_tpu.evaluation.postprocessing import compute_gae_for_sample_batch
from ray_tpu.execution.rollout_ops import synchronous_parallel_sample
from ray_tpu.execution.train_ops import train_one_step
from ray_tpu.policy.jax_policy import JaxPolicy


class A2CConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A2C)
        self.lr = 1e-4
        self.train_batch_size = 200
        self.rollout_fragment_length = 20
        self.use_gae = True
        self.lambda_ = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.entropy_coeff_schedule = None
        self.grad_clip = 40.0
        self.microbatch_size = None

    def training(
        self,
        *,
        use_gae: Optional[bool] = None,
        lambda_: Optional[float] = None,
        vf_loss_coeff: Optional[float] = None,
        entropy_coeff: Optional[float] = None,
        entropy_coeff_schedule=None,
        microbatch_size: Optional[int] = None,
        **kwargs,
    ) -> "A2CConfig":
        super().training(**kwargs)
        if use_gae is not None:
            self.use_gae = use_gae
        if lambda_ is not None:
            self.lambda_ = lambda_
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        if entropy_coeff_schedule is not None:
            self.entropy_coeff_schedule = entropy_coeff_schedule
        if microbatch_size is not None:
            self.microbatch_size = microbatch_size
        return self

    def to_dict(self) -> Dict:
        d = super().to_dict()
        d["lambda"] = d.pop("lambda_", 1.0)
        return d


class A2CJaxPolicy(JaxPolicy):
    """Vanilla actor-critic loss (reference a3c_torch_policy.py)."""

    # loss never reads NEXT_OBS; don't ship a second obs column
    _ship_next_obs = False

    def loss(self, params, batch, rng, coeffs):
        cfg = self.config
        dist_inputs, values, _ = self.model_forward_train(params, batch)
        dist = self.dist_class(dist_inputs)
        logp = dist.logp(batch[SampleBatch.ACTIONS])
        adv = batch[SampleBatch.ADVANTAGES]
        pi_loss = -jnp.mean(logp * adv)
        vf_loss = jnp.mean(
            jnp.square(values - batch[SampleBatch.VALUE_TARGETS])
        )
        entropy = jnp.mean(dist.entropy())
        total = (
            pi_loss
            + cfg.get("vf_loss_coeff", 0.5) * vf_loss
            - coeffs["entropy_coeff"] * entropy
        )
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    def postprocess_trajectory(
        self, sample_batch, other_agent_batches=None, episode=None
    ):
        return compute_gae_for_sample_batch(
            self, sample_batch, other_agent_batches, episode
        )


class A2C(Algorithm):
    _default_policy_class = A2CJaxPolicy

    @classmethod
    def get_default_config(cls) -> A2CConfig:
        return A2CConfig(cls)

    def training_step(self) -> Dict:
        train_batch = synchronous_parallel_sample(
            worker_set=self.workers,
            max_env_steps=self.config["train_batch_size"],
        )
        self._counters[NUM_ENV_STEPS_SAMPLED] += train_batch.env_steps()
        info = train_one_step(self, train_batch)
        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        return info


class A3CConfig(A2CConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A3C)


class A3C(A2C):
    """Async gradient-parallel flavor (reference a3c.py:191): each
    ready worker computes gradients on its own sample; the driver
    applies them and returns fresh weights to that worker only."""

    def training_step(self) -> Dict:
        workers = self.workers.remote_workers()
        if not workers:
            return super().training_step()
        policy = self.get_policy()
        info = {}

        def sample_and_grad(worker):
            batch = worker.sample()
            grads, g_info = worker.compute_gradients(batch)
            return grads, g_info, batch.env_steps()

        refs = [w.apply.remote(sample_and_grad) for w in workers]
        ready, _ = ray.wait(
            refs, num_returns=1, timeout=60.0
        )
        for ref in ready:
            grads, g_info, steps = ray.get(ref)
            policy.apply_gradients(grads)
            self._counters[NUM_ENV_STEPS_SAMPLED] += steps
            info = {DEFAULT_POLICY_ID: g_info}
        self.workers.sync_weights(
            global_vars={
                "timestep": self._counters[NUM_ENV_STEPS_SAMPLED]
            }
        )
        return info
