"""Host-side trajectory postprocessing (GAE).

Counterpart of the reference's ``rllib/evaluation/postprocessing.py``
(``compute_advantages :76``, ``compute_gae_for_sample_batch :140``). Runs in
numpy on CPU rollout actors. The learner-side jit GAE fast path lives in
``ray_tpu/ops/gae.py``; this module is the parity path used when workers
postprocess (needed for replay-based algorithms and multi-agent callbacks).
"""

from __future__ import annotations

import numpy as np
import scipy.signal

from ray_tpu.data.sample_batch import SampleBatch


def discount_cumsum(x: np.ndarray, gamma: float) -> np.ndarray:
    """y[t] = sum_k gamma^k x[t+k] via an IIR filter (vectorized)."""
    return scipy.signal.lfilter(
        [1], [1, float(-gamma)], x[::-1], axis=0
    )[::-1].astype(np.float32)


def compute_advantages(
    rollout: SampleBatch,
    last_r: float,
    gamma: float = 0.9,
    lambda_: float = 1.0,
    use_gae: bool = True,
    use_critic: bool = True,
) -> SampleBatch:
    """Reference postprocessing.py:76, same semantics and column names."""
    rewards = np.asarray(rollout[SampleBatch.REWARDS], np.float32)
    if use_gae:
        vpred = np.asarray(rollout[SampleBatch.VF_PREDS], np.float32)
        vpred_t = np.concatenate([vpred, np.array([last_r], np.float32)])
        delta_t = rewards + gamma * vpred_t[1:] - vpred_t[:-1]
        advantages = discount_cumsum(delta_t, gamma * lambda_)
        rollout[SampleBatch.ADVANTAGES] = advantages
        rollout[SampleBatch.VALUE_TARGETS] = (
            advantages + vpred
        ).astype(np.float32)
    else:
        rewards_plus_v = np.concatenate(
            [rewards, np.array([last_r], np.float32)]
        )
        discounted_returns = discount_cumsum(rewards_plus_v, gamma)[:-1]
        if use_critic:
            vpred = np.asarray(rollout[SampleBatch.VF_PREDS], np.float32)
            rollout[SampleBatch.ADVANTAGES] = discounted_returns - vpred
            rollout[SampleBatch.VALUE_TARGETS] = discounted_returns
        else:
            rollout[SampleBatch.ADVANTAGES] = discounted_returns
            rollout[SampleBatch.VALUE_TARGETS] = np.zeros_like(
                discounted_returns
            )
    rollout[SampleBatch.ADVANTAGES] = rollout[
        SampleBatch.ADVANTAGES
    ].astype(np.float32)
    return rollout


def compute_gae_for_sample_batch(
    policy,
    sample_batch: SampleBatch,
    other_agent_batches=None,
    episode=None,
) -> SampleBatch:
    """Reference postprocessing.py:140: bootstrap the fragment tail with
    V(s_T) when truncated, 0 when terminated."""
    terminated = bool(sample_batch[SampleBatch.TERMINATEDS][-1])
    truncated = bool(
        sample_batch.get(
            SampleBatch.TRUNCATEDS,
            np.zeros(len(sample_batch), bool),
        )[-1]
    )
    if terminated and not truncated:
        last_r = 0.0
    else:
        last_obs = sample_batch[SampleBatch.NEXT_OBS][-1]
        state = None
        if policy.is_recurrent:
            last = getattr(sample_batch, "last_state_out", None)
            if last is not None:
                # sampler side-channel: state AFTER the last step
                state = [np.asarray(s)[None] for s in last]
            elif "state_out_0" in sample_batch:
                state = [
                    sample_batch[f"state_out_{i}"][-1][None]
                    for i in range(len(policy.get_initial_state()))
                ]
            else:
                state = [
                    s[None]
                    for s in (
                        np.asarray(x)
                        for x in policy.get_initial_state()
                    )
                ]
        last_r = float(policy.value_batch(last_obs[None], state)[0])
    return compute_advantages(
        sample_batch,
        last_r,
        policy.config.get("gamma", 0.99),
        policy.config.get("lambda", 1.0),
        use_gae=policy.config.get("use_gae", True),
        use_critic=policy.config.get("use_critic", True),
    )
