"""Trajectory-view collection: materialize declared shifted/window
columns while sampling.

Counterpart of the reference's trajectory view API
(``rllib/policy/view_requirement.py:15`` +
``rllib/evaluation/collectors/simple_list_collector.py`` build_* —
the collectors read each policy's ``view_requirements`` and assemble
both the compute_actions input dict and the train batch from the
declarations). Here the :class:`ViewCollector` owns the derived
(``data_col``-shifted) requirements: per-env bounded history buffers,
zero-fill before the episode start, window stacking on a new leading
axis, and a clean cut at episode boundaries.

The base columns (obs/actions/rewards/...) and the hot prev-1
shortcuts (PREV_ACTIONS / PREV_REWARDS) stay on the sampler's direct
path; everything else a policy or model declares — frame windows for
attention models, n-step-back actions, custom debug views — flows
through here with no sampler changes.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch

# columns the sampler itself produces every step
_BASE = {
    SampleBatch.OBS,
    SampleBatch.NEXT_OBS,
    SampleBatch.ACTIONS,
    SampleBatch.REWARDS,
    SampleBatch.TERMINATEDS,
    SampleBatch.TRUNCATEDS,
    SampleBatch.EPS_ID,
    SampleBatch.AGENT_INDEX,
    SampleBatch.T,
    SampleBatch.PREV_ACTIONS,
    SampleBatch.PREV_REWARDS,
}


def derived_requirements(view_requirements: Dict) -> Dict:
    """The requirements the ViewCollector must materialize: anything
    keyed off another column via ``data_col`` (except the sampler's
    built-in prev-1 shortcuts and identity views)."""
    out = {}
    for key, req in (view_requirements or {}).items():
        if key in _BASE:
            continue
        data_col = getattr(req, "data_col", None)
        if data_col is None:
            continue  # produced by the policy itself (extra fetches)
        out[key] = req
    return out


class ViewCollector:
    def __init__(self, view_requirements: Dict, num_envs: int):
        self.reqs = derived_requirements(view_requirements)
        self.lookback = max(
            [r.lookback for r in self.reqs.values()], default=0
        )
        # per-env, per-source-column bounded history of PAST steps
        self._hist: List[Dict[str, deque]] = [
            {} for _ in range(num_envs)
        ]

    @property
    def active(self) -> bool:
        return bool(self.reqs)

    # -- helpers ---------------------------------------------------------

    def _zero(self, req, like: Optional[np.ndarray]) -> np.ndarray:
        if like is not None:
            return np.zeros_like(like)
        space = getattr(req, "space", None)
        if space is not None:
            return np.zeros(space.shape, space.dtype)
        raise ValueError(
            f"view requirement on {req.data_col!r} needs a `space` to "
            "zero-fill before any value was collected"
        )

    def _view_at(self, hist: deque, shift: int, req, like):
        """Value of the source column ``shift`` steps back (shift<=0;
        0 = the value being added this step, passed via ``like``)."""
        if shift == 0:
            if like is None:
                raise ValueError(
                    f"{req.data_col!r} shift 0 view has no current value"
                )
            return np.asarray(like)
        idx = len(hist) + shift
        if idx < 0:
            return self._zero(req, like if like is not None
                              else (hist[0] if hist else None))
        return hist[idx]

    def _materialize(self, env_i: int, key: str, req, current):
        hist = self._hist[env_i].setdefault(
            req.data_col, deque(maxlen=max(self.lookback, 1))
        )
        if req.is_window:
            return np.stack(
                [
                    self._view_at(hist, s, req, current)
                    for s in range(req.shift_from, req.shift_to + 1)
                ]
            )
        return self._view_at(hist, req.shift_from, req, current)

    # -- sampler hooks ---------------------------------------------------

    def compute_action_views(
        self, env_i: int, current: Dict[str, np.ndarray]
    ) -> Dict[str, np.ndarray]:
        """Views for this step's compute_actions call. ``current``
        maps source columns to their this-step values (the not yet
        recorded ones, e.g. the current obs); shift-0 references read
        from it."""
        out = {}
        for key, req in self.reqs.items():
            if not req.used_for_compute_actions:
                continue
            out[key] = self._materialize(
                env_i, key, req, current.get(req.data_col)
            )
        return out

    def annotate_row(self, env_i: int, row: Dict) -> None:
        """Write the declared train-time views into the row, then
        absorb the row's source columns into history. Call AFTER the
        sampler filled the base columns for this step."""
        for key, req in self.reqs.items():
            if not req.used_for_training:
                continue
            if key in row:
                continue  # policy extras win
            row[key] = self._materialize(
                env_i, key, req, row.get(req.data_col)
            )
        if self.lookback > 0:
            needed = {r.data_col for r in self.reqs.values()}
            hist_i = self._hist[env_i]
            for col in needed:
                if col in row:
                    hist_i.setdefault(
                        col, deque(maxlen=max(self.lookback, 1))
                    ).append(np.asarray(row[col]))

    def reset_env(self, env_i: int) -> None:
        """Episode boundary: views never reach into the previous
        episode."""
        for h in self._hist[env_i].values():
            h.clear()
