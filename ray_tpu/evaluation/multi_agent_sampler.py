"""Multi-agent synchronous sampler.

Counterpart of the reference's multi-agent path through
``rllib/evaluation/sampler.py _env_runner :531`` +
``SimpleListCollector`` (``collectors/simple_list_collector.py:523``): one
MultiAgentEnv, per-agent trajectories routed to policies via
``policy_mapping_fn``, emitted as a MultiAgentBatch keyed by policy id.

Per step, observations are grouped by policy so each policy does ONE batched
``compute_actions`` call across its agents (the reference batches the same
way through the collector's forward-pass buffers).
"""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from ray_tpu.data.sample_batch import (
    MultiAgentBatch,
    SampleBatch,
    concat_samples,
)
from ray_tpu.evaluation.episode import EpisodeRecord
from ray_tpu.evaluation.metrics import RolloutMetrics
from ray_tpu.evaluation.sampler import _EnvSlotCollector, unsquash_action


class MultiAgentSyncSampler:
    def __init__(
        self,
        *,
        env,
        policy_map: Dict,
        policy_mapping_fn: Callable,
        preprocessors: Dict,
        obs_filters: Dict,
        rollout_fragment_length: int = 200,
        batch_mode: str = "truncate_episodes",
        normalize_actions: bool = True,
    ):
        self.env = env
        self.policy_map = policy_map
        self.policy_mapping_fn = policy_mapping_fn
        self.preprocessors = preprocessors
        self.obs_filters = obs_filters
        self.frag_len = rollout_fragment_length
        self.batch_mode = batch_mode
        self.normalize_actions = normalize_actions

        self.collectors: Dict = {}  # agent_id -> _EnvSlotCollector
        self.agent_policy: Dict = {}
        self.metrics_queue: List[RolloutMetrics] = []
        self.episode = EpisodeRecord()
        self._reset_env()

    def _transform(self, pid, obs):
        prep = self.preprocessors.get(pid)
        if prep is not None:
            obs = prep.transform(obs)
        filt = self.obs_filters.get(pid)
        if filt is not None:
            obs = filt(obs)
        return np.asarray(obs)

    def _reset_env(self):
        raw_obs, _ = self.env.reset()
        # re-consult the mapping fn each episode (league matchmaking
        # assigns fresh opponents per game — reference policy_mapping_fn
        # receives the episode for exactly this)
        self.agent_policy = {}
        self.cur_obs = {}
        for aid, o in raw_obs.items():
            pid = self._pid(aid)
            self.cur_obs[aid] = self._transform(pid, o)
        self.episode = EpisodeRecord()

    def _pid(self, aid):
        if aid not in self.agent_policy:
            self.agent_policy[aid] = self.policy_mapping_fn(aid)
        return self.agent_policy[aid]

    def sample(self) -> MultiAgentBatch:
        out: Dict[str, List[SampleBatch]] = {}
        env_steps = 0
        while env_steps < self.frag_len:
            env_steps += 1
            self._step_once(out)
        # flush remaining trajectories at fragment boundary
        for aid in list(self.collectors):
            self._flush_agent(aid, out, done=False)
        policy_batches = {
            pid: concat_samples(bs) for pid, bs in out.items() if bs
        }
        return MultiAgentBatch(policy_batches, env_steps)

    def _step_once(self, out):
        # group agents by policy → one batched forward per policy
        by_policy: Dict[str, List] = {}
        for aid, obs in self.cur_obs.items():
            by_policy.setdefault(self._pid(aid), []).append(aid)

        actions: Dict = {}
        extras_by_agent: Dict = {}
        for pid, aids in by_policy.items():
            policy = self.policy_map[pid]
            obs_batch = np.stack([self.cur_obs[a] for a in aids])
            acts, _, extras = policy.compute_actions(obs_batch)
            for j, aid in enumerate(aids):
                actions[aid] = acts[j]
                extras_by_agent[aid] = {
                    k: np.asarray(v[j]) for k, v in extras.items()
                }

        env_actions = {
            aid: (
                unsquash_action(
                    a, self.policy_map[self._pid(aid)].action_space
                )
                if self.normalize_actions
                else a
            )
            for aid, a in actions.items()
        }
        next_obs, rewards, terms, truncs, infos = self.env.step(env_actions)
        all_done = terms.get("__all__", False) or truncs.get(
            "__all__", False
        )

        for aid in actions:
            pid = self._pid(aid)
            term = bool(terms.get(aid, False))
            trunc = bool(truncs.get(aid, False))
            has_next = aid in next_obs
            t_obs = (
                self._transform(pid, next_obs[aid])
                if has_next
                else self.cur_obs[aid]
            )
            coll = self.collectors.setdefault(aid, _EnvSlotCollector())
            coll.add(
                {
                    SampleBatch.OBS: self.cur_obs[aid],
                    SampleBatch.NEXT_OBS: t_obs,
                    SampleBatch.ACTIONS: np.asarray(actions[aid]),
                    SampleBatch.REWARDS: np.float32(
                        rewards.get(aid, 0.0)
                    ),
                    SampleBatch.TERMINATEDS: np.bool_(term or all_done),
                    SampleBatch.TRUNCATEDS: np.bool_(trunc),
                    SampleBatch.EPS_ID: np.int64(self.episode.episode_id),
                    SampleBatch.AGENT_INDEX: np.int64(
                        hash(aid) % (2**31)
                    ),
                    **extras_by_agent[aid],
                }
            )
            self.episode.add(float(rewards.get(aid, 0.0)), aid)
            if term or trunc or all_done:
                self._flush_agent(aid, out, done=True)
                self.cur_obs.pop(aid, None)
            elif has_next:
                self.cur_obs[aid] = t_obs

        for aid, o in next_obs.items():
            if aid not in self.cur_obs and not (
                terms.get(aid, False) or truncs.get(aid, False)
            ) and not all_done:
                self.cur_obs[aid] = self._transform(self._pid(aid), o)

        if all_done or not self.cur_obs:
            self.metrics_queue.append(
                RolloutMetrics(
                    self.episode.length // max(1, len(self.agent_policy)),
                    self.episode.total_reward,
                    {
                        (aid, self._pid(aid)): r
                        for aid, r in self.episode.agent_rewards.items()
                    },
                )
            )
            self._reset_env()

    def _flush_agent(self, aid, out, done: bool):
        coll = self.collectors.get(aid)
        if coll is None or coll.count == 0:
            return
        pid = self._pid(aid)
        batch = coll.flush()
        policy = self.policy_map[pid]
        expl = getattr(policy, "exploration", None)
        if expl is not None:
            batch = expl.postprocess_trajectory(policy, batch)
        batch = policy.postprocess_trajectory(batch)
        out.setdefault(pid, []).append(batch)
        if done:
            self.collectors.pop(aid, None)

    def get_metrics(self) -> List[RolloutMetrics]:
        m = self.metrics_queue
        self.metrics_queue = []
        return m
