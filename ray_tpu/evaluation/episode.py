"""Episode bookkeeping for metrics (reference
``rllib/evaluation/episode.py`` Episode, trimmed to the metric-bearing
fields)."""

from __future__ import annotations

import random
from typing import Dict


class EpisodeRecord:
    def __init__(self):
        self.episode_id = random.getrandbits(62)
        self.total_reward = 0.0
        self.length = 0
        self.agent_rewards: Dict = {}
        # callback surface (reference Episode.user_data /
        # .custom_metrics): user_data is per-episode scratch space;
        # custom_metrics scalars aggregate into the training result
        self.user_data: Dict = {}
        self.custom_metrics: Dict[str, float] = {}
        self.last_info: Dict = {}

    def add(self, reward: float, agent_id=None):
        self.total_reward += reward
        self.length += 1
        if agent_id is not None:
            self.agent_rewards[agent_id] = (
                self.agent_rewards.get(agent_id, 0.0) + reward
            )
