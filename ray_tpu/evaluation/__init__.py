from ray_tpu.evaluation.rollout_worker import RolloutWorker
from ray_tpu.evaluation.worker_set import WorkerSet
from ray_tpu.evaluation.sampler import SyncSampler
from ray_tpu.evaluation.postprocessing import (
    compute_advantages,
    compute_gae_for_sample_batch,
)
from ray_tpu.evaluation.metrics import RolloutMetrics, summarize_episodes

__all__ = [
    "RolloutWorker",
    "WorkerSet",
    "SyncSampler",
    "compute_advantages",
    "compute_gae_for_sample_batch",
    "RolloutMetrics",
    "summarize_episodes",
]
