"""Synchronous vectorized sampler — the rollout hot loop.

Counterpart of the reference's ``rllib/evaluation/sampler.py``
(``SyncSampler :168``, the ``_env_runner`` generator ``:531``) fused with the
trajectory collector (``collectors/simple_list_collector.py:523``). The loop
is batched across a VectorEnv: one ``policy.compute_actions`` call per step
covers every sub-env (a single jitted CPU forward), actions fan back out to
the envs, and per-env collectors assemble fixed-length fragments
("truncate_episodes" mode) or whole episodes ("complete_episodes").
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch, concat_samples
from ray_tpu.evaluation.episode import EpisodeRecord
from ray_tpu.evaluation.metrics import RolloutMetrics
from ray_tpu.util import tracing

try:
    from gymnasium import spaces
except ImportError:  # pragma: no cover
    spaces = None


def unsquash_action(action, space):
    """Map a [-1,1]-normalized action to the space bounds (reference
    ``rllib/utils/spaces/space_utils.py`` unsquash_action)."""
    if isinstance(space, spaces.Box) and np.all(np.isfinite(space.low)):
        a = np.clip(action, -1.0, 1.0)
        return space.low + (a + 1.0) * (space.high - space.low) / 2.0
    return action


def clip_action(action, space):
    if isinstance(space, spaces.Box):
        return np.clip(action, space.low, space.high)
    return action


class _EnvSlotCollector:
    """Per-sub-env trajectory accumulator."""

    def __init__(self):
        self.columns: Dict[str, List] = {}
        self.count = 0

    def add(self, row: Dict):
        for k, v in row.items():
            self.columns.setdefault(k, []).append(v)
        self.count += 1

    def flush(self) -> SampleBatch:
        batch = SampleBatch(
            {
                k: (np.stack(v) if not isinstance(v[0], dict) else v)
                for k, v in self.columns.items()
            }
        )
        self.columns = {}
        self.count = 0
        return batch


def transform_obs(preprocessor, obs_filter, obs):
    """The shared obs pipeline: preprocessor (one-hot/flatten) then
    observation filter. Used by the samplers AND PolicyServerInput so
    the two paths cannot drift."""
    if preprocessor is not None:
        obs = preprocessor.transform(obs)
    if obs_filter is not None:
        obs = obs_filter(obs)
    return np.asarray(obs)


def postprocess_batch(policy, batch):
    """Exploration first (intrinsic rewards land before GAE sees
    them), then the policy's own postprocessing."""
    expl = getattr(policy, "exploration", None)
    if expl is not None:
        batch = expl.postprocess_trajectory(policy, batch)
    return policy.postprocess_trajectory(batch)


class SyncSampler:
    def __init__(
        self,
        *,
        vector_env,
        policy,
        preprocessor=None,
        obs_filter=None,
        rollout_fragment_length: int = 200,
        batch_mode: str = "truncate_episodes",
        episode_horizon: Optional[int] = None,
        clip_actions: bool = False,
        normalize_actions: bool = True,
        callbacks=None,
        flush_on_episode_end: bool = True,
    ):
        self.env = vector_env
        self.policy = policy
        self.preprocessor = preprocessor
        self.obs_filter = obs_filter
        self.frag_len = rollout_fragment_length
        self.batch_mode = batch_mode
        self.horizon = episode_horizon
        self.clip_actions = clip_actions
        self.normalize_actions = normalize_actions
        self.callbacks = callbacks
        # False → fixed-size unrolls that may span episode boundaries
        # (IMPALA/V-trace mode: dones inside the fragment carry the reset
        # information; no padding or re-chopping needed on TPU).
        self.flush_on_episode_end = flush_on_episode_end

        n = self.env.num_envs
        self.collectors = [_EnvSlotCollector() for _ in range(n)]
        self.episodes = [EpisodeRecord() for _ in range(n)]
        if self.callbacks is not None:
            for i in range(n):
                self._cb("on_episode_start", i)
        self.metrics_queue: List[RolloutMetrics] = []
        # AsyncSampler appends from its thread while the driver swaps
        import threading as _threading

        self._metrics_lock = _threading.Lock()
        self.unroll_id = 0

        raw_obs, _ = self.env.vector_reset()
        self.cur_obs = [self._transform(o) for o in raw_obs]
        init_state = self.policy.get_initial_state()
        self.states = [
            [s.copy() for s in init_state] for _ in range(n)
        ]
        self._has_state = bool(init_state)
        # View-requirement-driven shifted columns (reference
        # view_requirement.py:15 shift=-1): populate prev_actions /
        # prev_rewards only when the policy asks for them.
        vr = getattr(self.policy, "view_requirements", {}) or {}
        self._want_prev_actions = SampleBatch.PREV_ACTIONS in vr
        self._want_prev_rewards = SampleBatch.PREV_REWARDS in vr
        self._prev_actions = [None] * n
        self._prev_rewards = [np.float32(0.0)] * n
        # everything else the policy/model declares (frame windows,
        # n-step-back columns, ...) materializes from the declaration
        # alone (reference simple_list_collector.py build_*)
        from ray_tpu.evaluation.view_collector import ViewCollector

        self._views = ViewCollector(vr, n)

    def _transform(self, obs):
        return transform_obs(self.preprocessor, self.obs_filter, obs)

    def _cb(self, hook: str, env_index: int) -> None:
        """Invoke one user callback hook (reference DefaultCallbacks);
        a raising callback fails sampling loudly, as in the
        reference — silent swallowing would hide user bugs."""
        getattr(self.callbacks, hook)(
            worker=None,
            base_env=self.env,
            policies={"default_policy": self.policy},
            episode=self.episodes[env_index],
            env_index=env_index,
        )

    # -- main loop -------------------------------------------------------

    def sample(self) -> SampleBatch:
        # per-rollout span: on a remote worker this parents under the
        # "actor:RolloutWorker.sample" execution span the submitted
        # trace context opened (core/worker_proc.py), so fragments
        # line up against the driver's iteration in the chrome trace
        with tracing.start_span("sampler:collect") as span:
            result = self._sample(span)
        return result

    def _sample(self, span) -> SampleBatch:
        n = self.env.num_envs
        out: List[SampleBatch] = []
        if self.batch_mode == "truncate_episodes":
            for _ in range(self.frag_len):
                self._step_once(out)
            for i in range(n):
                self._flush_slot(i, out)
        else:  # complete_episodes
            target = self.frag_len * n
            steps = 0
            while steps < target or any(
                c.count > 0 for c in self.collectors
            ):
                done_any = self._step_once(out)
                steps += n
                if steps >= target and not any(
                    c.count > 0 for c in self.collectors
                ):
                    break
        batches = [b for b in out if b.count > 0]
        result = (
            concat_samples(batches) if batches else SampleBatch()
        )
        span.set_attribute("env_steps", int(result.env_steps()))
        span.set_attribute("fragments", len(batches))
        if self.callbacks is not None:
            self.callbacks.on_sample_end(worker=None, samples=result)
        return result

    def _step_once(self, out: List[SampleBatch]) -> bool:
        n = self.env.num_envs
        obs_batch = np.stack(self.cur_obs)
        state_batches = None
        if self._has_state:
            state_batches = [
                np.stack([self.states[i][k] for i in range(n)])
                for k in range(len(self.states[0]))
            ]
        prev_kwargs = {}
        if self._want_prev_actions:
            shape = self.env.action_space.shape
            zero = np.zeros(
                shape or (), np.float32 if shape else np.int64
            )
            prev_kwargs["prev_action_batch"] = np.stack(
                [zero if a is None else a for a in self._prev_actions]
            )
        if self._want_prev_rewards:
            prev_kwargs["prev_reward_batch"] = np.asarray(
                self._prev_rewards, np.float32
            )
        if self._views.active:
            per_env = [
                self._views.compute_action_views(
                    i, {SampleBatch.OBS: self.cur_obs[i]}
                )
                for i in range(n)
            ]
            for k in per_env[0]:
                prev_kwargs[k] = np.stack([pe[k] for pe in per_env])
        actions, state_out, extras = self.policy.compute_actions(
            obs_batch, state_batches, explore=True, **prev_kwargs
        )

        env_actions = []
        for i in range(n):
            a = actions[i]
            if self.normalize_actions:
                a = unsquash_action(a, self.env.action_space)
            elif self.clip_actions:
                a = clip_action(a, self.env.action_space)
            env_actions.append(a)

        next_obs, rewards, terms, truncs, infos = self.env.vector_step(
            env_actions
        )
        done_any = False
        for i in range(n):
            t_obs = self._transform(next_obs[i])
            row = {
                SampleBatch.OBS: self.cur_obs[i],
                SampleBatch.NEXT_OBS: t_obs,
                SampleBatch.ACTIONS: np.asarray(actions[i]),
                SampleBatch.REWARDS: np.float32(rewards[i]),
                SampleBatch.TERMINATEDS: np.bool_(terms[i]),
                SampleBatch.TRUNCATEDS: np.bool_(truncs[i]),
                SampleBatch.EPS_ID: np.int64(self.episodes[i].episode_id),
                SampleBatch.AGENT_INDEX: np.int64(i),
                SampleBatch.T: np.int64(self.episodes[i].length),
            }
            for k, v in extras.items():
                row[k] = np.asarray(v[i])
            if self._has_state:
                for k in range(len(self.states[i])):
                    row[f"state_in_{k}"] = self.states[i][k]
            if self._want_prev_actions:
                row[SampleBatch.PREV_ACTIONS] = (
                    np.zeros_like(np.asarray(actions[i]))
                    if self._prev_actions[i] is None
                    else self._prev_actions[i]
                )
                self._prev_actions[i] = np.asarray(actions[i])
            if self._want_prev_rewards:
                row[SampleBatch.PREV_REWARDS] = self._prev_rewards[i]
                self._prev_rewards[i] = np.float32(rewards[i])
            if self._views.active:
                self._views.annotate_row(i, row)
            self.collectors[i].add(row)
            self.episodes[i].add(float(rewards[i]))
            if self.callbacks is not None:
                self.episodes[i].last_info = infos[i] or {}
                self._cb("on_episode_step", i)

            if self._has_state:
                self.states[i] = [np.asarray(s[i]) for s in state_out]

            ep_done = terms[i] or truncs[i]
            if (
                self.horizon
                and self.episodes[i].length >= self.horizon
            ):
                ep_done = True
                truncs[i] = True
            if ep_done:
                done_any = True
                self._prev_actions[i] = None
                self._prev_rewards[i] = np.float32(0.0)
                if self._views.active:
                    self._views.reset_env(i)
                if self.callbacks is not None:
                    self._cb("on_episode_end", i)
                if self.flush_on_episode_end:
                    self._flush_slot(i, out)
                with self._metrics_lock:
                    self.metrics_queue.append(
                        RolloutMetrics(
                            self.episodes[i].length,
                            self.episodes[i].total_reward,
                            custom_metrics=dict(
                                self.episodes[i].custom_metrics
                            ),
                        )
                    )
                self.episodes[i] = EpisodeRecord()
                if self.callbacks is not None:
                    self._cb("on_episode_start", i)
                raw, _ = self.env.reset_at(i)
                self.cur_obs[i] = self._transform(raw)
                if self._has_state:
                    self.states[i] = [
                        s.copy()
                        for s in self.policy.get_initial_state()
                    ]
            else:
                self.cur_obs[i] = t_obs
        return done_any

    def _flush_slot(self, i: int, out: List[SampleBatch]) -> None:
        if self.collectors[i].count == 0:
            return
        batch = self.collectors[i].flush()
        batch[SampleBatch.UNROLL_ID] = np.full(
            batch.count, self.unroll_id, np.int64
        )
        self.unroll_id += 1
        if self._has_state:
            # side-channel for GAE's recurrent bootstrap: only the
            # state AFTER the fragment's last step is ever needed, so
            # don't pay a per-row state_out column for it
            batch.last_state_out = [
                np.asarray(s) for s in self.states[i]
            ]
        with tracing.start_span(
            "sampler:postprocess", env_index=i, rows=batch.count
        ):
            batch = postprocess_batch(self.policy, batch)
        # shrink the fragment before it leaves the worker (framestack
        # dedup — policies opt in via compress_for_shipping)
        compress = getattr(self.policy, "compress_for_shipping", None)
        if compress is not None:
            batch = compress(batch)
        out.append(batch)

    def get_metrics(self) -> List[RolloutMetrics]:
        with self._metrics_lock:
            out = self.metrics_queue
            self.metrics_queue = []
        return out


class AsyncSampler:
    """Background-thread sampler (reference ``sampler.py:320``
    AsyncSampler): env stepping + postprocessing run continuously on a
    daemon thread, queueing finished fragments; ``sample()`` pops. Use
    for slow/IO-bound envs so env stepping overlaps learning — policy
    weight swaps are atomic (the same sharing contract as IMPALA's
    learner thread)."""

    def __init__(self, *, queue_size: int = 8, **sync_kwargs):
        import queue as _queue
        import threading

        self._sync = SyncSampler(**sync_kwargs)
        self.policy = self._sync.policy
        self._queue: "_queue.Queue" = _queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._error = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="async_sampler"
        )
        self._thread.start()

    def _run(self):
        import queue as _queue

        while not self._stop.is_set():
            try:
                batch = self._sync.sample()
            except Exception as e:  # surface on next sample() call
                self._error = e
                return
            while not self._stop.is_set():
                try:
                    self._queue.put(batch, timeout=0.5)
                    break
                except _queue.Full:
                    continue

    def sample(self) -> SampleBatch:
        import queue as _queue

        while True:
            if self._error is not None:
                raise self._error
            try:
                return self._queue.get(timeout=1.0)
            except _queue.Empty:
                if not self._thread.is_alive() and self._error is None:
                    raise RuntimeError("async sampler thread died")

    def get_metrics(self) -> List[RolloutMetrics]:
        return self._sync.get_metrics()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
