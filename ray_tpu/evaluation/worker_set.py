"""WorkerSet: one local (learner) worker + N remote rollout actors.

Counterpart of the reference's ``rllib/evaluation/worker_set.py:50``
(``sync_weights :192``, ``foreach_worker :367``). Weight broadcast is a
single ``ray.put`` of the host pytree into the shared-memory object plane;
every actor maps the same segment (reference's object-store broadcast,
``worker_set.py:209-224``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.evaluation.rollout_worker import RolloutWorker
from ray_tpu.resilience.retry import RetryPolicy, probe_actors
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.utils.filter import MeanStdFilter

_ACTOR_DEAD_ERRORS = (
    ray.core.object_store.RayActorError,
    ray.core.object_store.WorkerCrashedError,
)


class WorkerSet:
    def __init__(
        self,
        *,
        env_creator,
        policy_cls=None,
        policy_specs=None,
        policy_mapping_fn=None,
        config: Dict,
        num_workers: int = 0,
        local_worker: bool = True,
    ):
        self._env_creator = env_creator
        self._policy_cls = policy_cls
        self._policy_specs = policy_specs
        self._policy_mapping_fn = policy_mapping_fn
        self._config = config
        self._remote_workers: List = []
        # the uniform retry/timeout/backoff schedule every driver-side
        # remote interaction below draws from (docs/resilience.md)
        self._retry = RetryPolicy.from_config(config)

        self._local_worker = None
        if local_worker:
            self._local_worker = RolloutWorker(
                env_creator=env_creator,
                policy_cls=policy_cls,
                policy_specs=policy_specs,
                policy_mapping_fn=policy_mapping_fn,
                config=config,
                worker_index=0,
                num_workers=num_workers,
            )
        if num_workers > 0:
            # the initial population needs no elastic-join sync: every
            # worker just built its policy from the same config/seed
            # the local worker did, and nothing has trained yet
            self.add_workers(num_workers, sync=False)

    def add_workers(
        self,
        num_workers: int,
        *,
        config_overrides: Optional[Dict] = None,
        sync: bool = True,
    ) -> None:
        """reference worker_set.py:234. ``config_overrides`` lets the
        recovery path hand replacements a modified config (e.g. an
        empty ``fault_injection`` spec so a recreated worker doesn't
        re-run its predecessor's death sentence). ``sync`` (default
        True — every mid-run join) queues the elastic-join
        weight+filter sync on the new actors; the constructor's
        initial population skips it."""
        if not ray.is_initialized():
            ray.init()
        RemoteWorker = ray.remote(RolloutWorker)
        start = len(self._remote_workers)
        # cross-host fleet: round-robin rollout actors over the named
        # cluster nodes ("any" = least-loaded); without the config key
        # all actors stay on the head host (core/cluster.py)
        nodes = self._config.get("worker_nodes") or []
        worker_config = {
            **self._config,
            "_mesh": None,
            **(config_overrides or {}),
        }
        # an injected kill/preemption models a lost host: the runtime's
        # in-place actor restart must not resurrect it (a restarted
        # process re-arms the injector's death sentence — fresh call
        # counts — and the chaos run never converges); the recovery
        # layer replaces the worker with a disarmed config instead
        fi = worker_config.get("fault_injection") or {}
        kill_armed = bool(
            fi.get("kill_worker") or fi.get("preempt_worker")
        )
        restarts = (
            3
            if self._config.get("recreate_failed_workers", False)
            and not kill_armed
            else 0
        )
        for i in range(num_workers):
            opts = dict(max_restarts=restarts)
            if nodes:
                opts["placement_node"] = nodes[(start + i) % len(nodes)]
            self._remote_workers.append(
                RemoteWorker.options(**opts).remote(
                    env_creator=self._env_creator,
                    policy_cls=self._policy_cls,
                    policy_specs=self._policy_specs,
                    policy_mapping_fn=self._policy_mapping_fn,
                    config=worker_config,
                    worker_index=start + i + 1,
                    num_workers=num_workers,
                )
            )
        # Elastic-join contract (docs/resilience.md): a joining worker
        # receives the CURRENT weights and observation-filter state
        # before its first sample call — actor calls execute in
        # submission order, so queuing the sync here, before the new
        # handles are ever returned to a sampling rotation, guarantees
        # it. A stale-policy first sample on scale-up would be silent
        # off-policy corruption for PPO (importance ratios computed
        # against ACTION_LOGP from weights the learner no longer has).
        if sync:
            self._sync_new_workers(self._remote_workers[start:])
        self._update_fleet_gauge()

    def _sync_new_workers(self, new_workers: List) -> None:
        if self._local_worker is None or not new_workers:
            return
        if not getattr(self._local_worker, "policy_map", None):
            return
        weights = self._local_worker.get_weights()
        filters = self._local_worker.get_filters()
        ref = ray.put(weights)
        for w in new_workers:
            try:
                w.set_weights.remote(ref)
                w.sync_filters.remote(filters)
            except _ACTOR_DEAD_ERRORS:
                continue

    def _update_fleet_gauge(self) -> None:
        telemetry_metrics.gauge(
            telemetry_metrics.ROLLOUT_WORKERS,
            "live remote rollout workers in this WorkerSet",
        ).set(float(len(self._remote_workers)))

    def local_worker(self) -> Optional[RolloutWorker]:
        return self._local_worker

    def remote_workers(self) -> List:
        return self._remote_workers

    def num_remote_workers(self) -> int:
        return len(self._remote_workers)

    # -- sync ------------------------------------------------------------

    def sync_weights(
        self,
        policies: Optional[List[str]] = None,
        global_vars: Optional[Dict] = None,
        to_worker_indices: Optional[List[int]] = None,
        inference_only: bool = False,
    ) -> None:
        """reference worker_set.py:192. ``inference_only`` ships each
        policy's acting subset (``get_inference_weights``) — on a
        tunneled TPU the device→host pull of full off-policy towers
        (critic + target) otherwise dominates the sync."""
        if self._local_worker is None:
            return
        weights = self._local_worker.get_weights(
            policies, inference_only=inference_only
        )
        if self._remote_workers:
            ref = ray.put(weights)
            targets = self._remote_workers
            if to_worker_indices is not None:
                targets = [
                    w
                    for i, w in enumerate(self._remote_workers)
                    if i + 1 in to_worker_indices
                ]
            for w in targets:
                try:
                    w.set_weights.remote(ref, global_vars)
                except _ACTOR_DEAD_ERRORS:
                    # a corpse must not abort the broadcast to the
                    # rest of the fleet (recovery replaces it later)
                    continue
        if global_vars:
            self._local_worker.set_global_vars(global_vars)

    def sync_filters(self) -> None:
        """Aggregate rollout filter deltas into the local worker's filters
        and broadcast the merged stats back (reference
        ``rllib/utils/filter_manager.py`` FilterManager.synchronize)."""
        if self._local_worker is None or not self._remote_workers:
            return
        remote_filters = []
        for w in self._remote_workers:
            try:
                remote_filters.append(
                    self._retry.call(
                        lambda w=w: ray.get(
                            w.get_filters.remote(True),
                            timeout=self._retry.timeout_s,
                        )
                    )
                )
            except _ACTOR_DEAD_ERRORS:
                continue  # dead worker contributes no filter delta
            except ray.core.object_store.GetTimeoutError:
                continue  # wedged worker: bounded skip, not a hang
        local = self._local_worker.filters
        for rf in remote_filters:
            for pid, f in rf.items():
                if pid in local and isinstance(f, MeanStdFilter):
                    local[pid].apply_changes(f, with_buffer=False)
        merged = {
            pid: f.as_serializable() for pid, f in local.items()
        }
        ref = ray.put(merged)
        for w in self._remote_workers:
            try:
                w.sync_filters.remote(ref)
            except _ACTOR_DEAD_ERRORS:
                continue

    # -- mapping ---------------------------------------------------------

    def _get_bounded(self, refs: List):
        """``ray.get`` under the retry policy: each attempt is bounded
        by the per-attempt timeout and timeouts re-wait on the backoff
        schedule (the refs keep computing across attempts — a retry
        never resubmits work), so a wedged actor costs
        ``max_attempts × timeout_s`` instead of an indefinite hang.
        Actor-death errors propagate immediately: callers of
        ``foreach_worker`` rely on them for the recreate protocol."""
        return self._retry.call(
            lambda: ray.get(refs, timeout=self._retry.timeout_s),
            retry_on=(ray.core.object_store.GetTimeoutError,),
        )

    def foreach_worker(self, fn: Callable) -> List:
        """reference worker_set.py:367."""
        out = []
        if self._local_worker is not None:
            out.append(fn(self._local_worker))
        out.extend(
            self._get_bounded(
                [w.apply.remote(fn) for w in self._remote_workers]
            )
        )
        return out

    def foreach_worker_with_index(self, fn: Callable) -> List:
        out = []
        if self._local_worker is not None:
            out.append(fn(self._local_worker, 0))
        refs = [
            w.apply.remote(fn, i + 1)
            for i, w in enumerate(self._remote_workers)
        ]
        out.extend(self._get_bounded(refs))
        return out

    def foreach_policy(self, fn: Callable) -> List:
        out = []
        for res in self.foreach_worker(
            lambda w: w.foreach_policy(fn)
        ):
            out.extend(res)
        return out

    def probe_unhealthy_workers(
        self, timeout_s: Optional[float] = None
    ) -> List[int]:
        """→ 1-based indices of workers that fail a ping (reference
        fault tolerance in worker_set / algorithm.try_recover). All
        pings fly in parallel under ONE wall-clock budget
        (``worker_health_probe_timeout_s``, default 10 s), so a single
        wedged actor delays the sweep by at most the budget instead of
        stalling the whole health check."""
        if timeout_s is None:
            timeout_s = float(
                self._config.get("worker_health_probe_timeout_s", 10.0)
            )
        return [
            i + 1
            for i in probe_actors(
                self._remote_workers, timeout_s=timeout_s
            )
        ]

    def remove_workers(self, workers: List) -> None:
        """Drop specific worker handles from the set (no ping probe).
        Used when an AsyncRequestsManager already OBSERVED the workers
        dead — probe_unhealthy_workers would spend a 30 s get-timeout
        per corpse rediscovering the fact."""
        drop = {id(w) for w in workers}
        self._remote_workers = [
            w for w in self._remote_workers if id(w) not in drop
        ]
        self._update_fleet_gauge()

    # replacements spin up with fault injection disarmed: an empty
    # spec also disables the RAY_TPU_FAULTS env fallback, so a
    # recreated worker doesn't re-run its predecessor's death sentence
    _REPLACEMENT_OVERRIDES = {"fault_injection": {}}

    def replace_failed_workers(self, dead: List) -> List:
        """Remove observed-dead workers and spawn replacements; returns
        the new handles (already weight-synced)."""
        if not dead:
            return []
        self.remove_workers(dead)
        before = len(self._remote_workers)
        # add_workers weight+filter-syncs the replacements before they
        # are returned (the elastic-join contract)
        self.add_workers(
            len(dead), config_overrides=self._REPLACEMENT_OVERRIDES
        )
        new = self._remote_workers[before:]
        telemetry_metrics.inc_worker_restarts(len(new))
        return new

    def recreate_failed_workers(self) -> int:
        """Probe the fleet (bounded), replace the unhealthy; returns
        the number of workers recreated."""
        bad = self.probe_unhealthy_workers()
        if not bad:
            return 0
        keep = [
            w
            for i, w in enumerate(self._remote_workers)
            if i + 1 not in bad
        ]
        self._remote_workers = keep
        self.add_workers(
            len(bad), config_overrides=self._REPLACEMENT_OVERRIDES
        )
        telemetry_metrics.inc_worker_restarts(len(bad))
        return len(bad)

    # -- elastic scaling (docs/resilience.md "elastic fleets") ----------

    def scale_up(self, num_workers: int) -> List:
        """Grow the fleet by ``num_workers``; returns the new handles,
        already weight+filter-synced (``add_workers``) so they can
        enter a sampling rotation immediately. Joiners spawn with
        fault injection disarmed — a scale-up must not inherit a
        chaos spec keyed on reused worker indices."""
        if num_workers <= 0:
            return []
        before = len(self._remote_workers)
        self.add_workers(
            num_workers, config_overrides=self._REPLACEMENT_OVERRIDES
        )
        return self._remote_workers[before:]

    def scale_to(self, n: int) -> Dict[str, List]:
        """Bring the fleet to exactly ``n`` remote workers. Scale-up
        spawns synced joiners; scale-down picks the newest workers as
        victims and removes them from the set (the caller — normally
        the FleetController — owns draining them first: harvesting
        in-flight work, merging filters, reaping the process).
        Returns ``{"added": [...], "removed": [...]}``."""
        n = max(0, int(n))
        cur = len(self._remote_workers)
        if n > cur:
            return {"added": self.scale_up(n - cur), "removed": []}
        if n < cur:
            victims = self._remote_workers[n:]
            self._remote_workers = self._remote_workers[:n]
            self._update_fleet_gauge()
            return {"added": [], "removed": victims}
        return {"added": [], "removed": []}

    def absorb_filters(self, remote_filters: Dict) -> None:
        """Merge one worker's flushed filter deltas into the local
        worker's filters (the drain protocol's last transfer — the
        same math ``sync_filters`` applies fleet-wide)."""
        if self._local_worker is None or not remote_filters:
            return
        local = self._local_worker.filters
        for pid, f in remote_filters.items():
            if pid in local and isinstance(f, MeanStdFilter):
                local[pid].apply_changes(f, with_buffer=False)

    @property
    def retry_policy(self) -> RetryPolicy:
        return self._retry

    def stop(self) -> None:
        if self._local_worker is not None:
            self._local_worker.stop()
        for w in self._remote_workers:
            try:
                w.stop.remote()
            except Exception:
                pass
