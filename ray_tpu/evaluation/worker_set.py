"""WorkerSet: one local (learner) worker + N remote rollout actors.

Counterpart of the reference's ``rllib/evaluation/worker_set.py:50``
(``sync_weights :192``, ``foreach_worker :367``). Weight broadcast is a
single ``ray.put`` of the host pytree into the shared-memory object plane;
every actor maps the same segment (reference's object-store broadcast,
``worker_set.py:209-224``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.evaluation.rollout_worker import RolloutWorker
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.utils.filter import MeanStdFilter


class WorkerSet:
    def __init__(
        self,
        *,
        env_creator,
        policy_cls=None,
        policy_specs=None,
        policy_mapping_fn=None,
        config: Dict,
        num_workers: int = 0,
        local_worker: bool = True,
    ):
        self._env_creator = env_creator
        self._policy_cls = policy_cls
        self._policy_specs = policy_specs
        self._policy_mapping_fn = policy_mapping_fn
        self._config = config
        self._remote_workers: List = []

        self._local_worker = None
        if local_worker:
            self._local_worker = RolloutWorker(
                env_creator=env_creator,
                policy_cls=policy_cls,
                policy_specs=policy_specs,
                policy_mapping_fn=policy_mapping_fn,
                config=config,
                worker_index=0,
                num_workers=num_workers,
            )
        if num_workers > 0:
            self.add_workers(num_workers)

    def add_workers(self, num_workers: int) -> None:
        """reference worker_set.py:234."""
        if not ray.is_initialized():
            ray.init()
        RemoteWorker = ray.remote(RolloutWorker)
        start = len(self._remote_workers)
        # cross-host fleet: round-robin rollout actors over the named
        # cluster nodes ("any" = least-loaded); without the config key
        # all actors stay on the head host (core/cluster.py)
        nodes = self._config.get("worker_nodes") or []
        for i in range(num_workers):
            opts = dict(
                max_restarts=int(
                    self._config.get("recreate_failed_workers", False)
                )
                and 3
            )
            if nodes:
                opts["placement_node"] = nodes[(start + i) % len(nodes)]
            self._remote_workers.append(
                RemoteWorker.options(**opts).remote(
                    env_creator=self._env_creator,
                    policy_cls=self._policy_cls,
                    policy_specs=self._policy_specs,
                    policy_mapping_fn=self._policy_mapping_fn,
                    config={**self._config, "_mesh": None},
                    worker_index=start + i + 1,
                    num_workers=num_workers,
                )
            )
        self._update_fleet_gauge()

    def _update_fleet_gauge(self) -> None:
        telemetry_metrics.gauge(
            telemetry_metrics.ROLLOUT_WORKERS,
            "live remote rollout workers in this WorkerSet",
        ).set(float(len(self._remote_workers)))

    def local_worker(self) -> Optional[RolloutWorker]:
        return self._local_worker

    def remote_workers(self) -> List:
        return self._remote_workers

    def num_remote_workers(self) -> int:
        return len(self._remote_workers)

    # -- sync ------------------------------------------------------------

    def sync_weights(
        self,
        policies: Optional[List[str]] = None,
        global_vars: Optional[Dict] = None,
        to_worker_indices: Optional[List[int]] = None,
        inference_only: bool = False,
    ) -> None:
        """reference worker_set.py:192. ``inference_only`` ships each
        policy's acting subset (``get_inference_weights``) — on a
        tunneled TPU the device→host pull of full off-policy towers
        (critic + target) otherwise dominates the sync."""
        if self._local_worker is None:
            return
        weights = self._local_worker.get_weights(
            policies, inference_only=inference_only
        )
        if self._remote_workers:
            ref = ray.put(weights)
            targets = self._remote_workers
            if to_worker_indices is not None:
                targets = [
                    w
                    for i, w in enumerate(self._remote_workers)
                    if i + 1 in to_worker_indices
                ]
            for w in targets:
                w.set_weights.remote(ref, global_vars)
        if global_vars:
            self._local_worker.set_global_vars(global_vars)

    def sync_filters(self) -> None:
        """Aggregate rollout filter deltas into the local worker's filters
        and broadcast the merged stats back (reference
        ``rllib/utils/filter_manager.py`` FilterManager.synchronize)."""
        if self._local_worker is None or not self._remote_workers:
            return
        remote_filters = ray.get(
            [w.get_filters.remote(True) for w in self._remote_workers]
        )
        local = self._local_worker.filters
        for rf in remote_filters:
            for pid, f in rf.items():
                if pid in local and isinstance(f, MeanStdFilter):
                    local[pid].apply_changes(f, with_buffer=False)
        merged = {
            pid: f.as_serializable() for pid, f in local.items()
        }
        ref = ray.put(merged)
        for w in self._remote_workers:
            w.sync_filters.remote(ref)

    # -- mapping ---------------------------------------------------------

    def foreach_worker(self, fn: Callable) -> List:
        """reference worker_set.py:367."""
        out = []
        if self._local_worker is not None:
            out.append(fn(self._local_worker))
        out.extend(
            ray.get([w.apply.remote(fn) for w in self._remote_workers])
        )
        return out

    def foreach_worker_with_index(self, fn: Callable) -> List:
        out = []
        if self._local_worker is not None:
            out.append(fn(self._local_worker, 0))
        refs = [
            w.apply.remote(fn, i + 1)
            for i, w in enumerate(self._remote_workers)
        ]
        out.extend(ray.get(refs))
        return out

    def foreach_policy(self, fn: Callable) -> List:
        out = []
        for res in self.foreach_worker(
            lambda w: w.foreach_policy(fn)
        ):
            out.extend(res)
        return out

    def probe_unhealthy_workers(self) -> List[int]:
        """→ indices of workers that fail a ping (reference fault
        tolerance in worker_set / algorithm.try_recover)."""
        bad = []
        refs = [
            (i, w.ping.remote())
            for i, w in enumerate(self._remote_workers)
        ]
        for i, ref in refs:
            try:
                ray.get(ref, timeout=30)
            except Exception:
                bad.append(i + 1)
        return bad

    def remove_workers(self, workers: List) -> None:
        """Drop specific worker handles from the set (no ping probe).
        Used when an AsyncRequestsManager already OBSERVED the workers
        dead — probe_unhealthy_workers would spend a 30 s get-timeout
        per corpse rediscovering the fact."""
        drop = {id(w) for w in workers}
        self._remote_workers = [
            w for w in self._remote_workers if id(w) not in drop
        ]
        self._update_fleet_gauge()

    def replace_failed_workers(self, dead: List) -> List:
        """Remove observed-dead workers and spawn replacements; returns
        the new handles (already weight-synced)."""
        if not dead:
            return []
        self.remove_workers(dead)
        before = len(self._remote_workers)
        self.add_workers(len(dead))
        new = self._remote_workers[before:]
        self.sync_weights()
        return new

    def recreate_failed_workers(self) -> None:
        bad = self.probe_unhealthy_workers()
        if not bad:
            return
        num = len(self._remote_workers)
        keep = [
            w
            for i, w in enumerate(self._remote_workers)
            if i + 1 not in bad
        ]
        self._remote_workers = keep
        self.add_workers(len(bad))
        self.sync_weights()

    def stop(self) -> None:
        if self._local_worker is not None:
            self._local_worker.stop()
        for w in self._remote_workers:
            try:
                w.stop.remote()
            except Exception:
                pass
