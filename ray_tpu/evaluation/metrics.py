"""Rollout metrics aggregation.

Counterpart of the reference's ``rllib/evaluation/metrics.py``
(collect_episodes / summarize_episodes).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


class RolloutMetrics:
    def __init__(self, episode_length: int, episode_reward: float,
                 agent_rewards: Dict | None = None,
                 custom_metrics: Dict | None = None):
        self.episode_length = episode_length
        self.episode_reward = episode_reward
        self.agent_rewards = agent_rewards or {}
        # user scalars from Episode.custom_metrics (callbacks)
        self.custom_metrics = custom_metrics or {}


def summarize_episodes(episodes: List[RolloutMetrics]) -> Dict:
    """reference metrics.py summarize_episodes."""
    rewards = [e.episode_reward for e in episodes]
    lengths = [e.episode_length for e in episodes]
    policy_rewards: Dict[str, List[float]] = {}
    for e in episodes:
        for (aid, pid), r in e.agent_rewards.items():
            policy_rewards.setdefault(pid, []).append(r)
    out = {
        "episode_reward_max": float(np.max(rewards)) if rewards else np.nan,
        "episode_reward_min": float(np.min(rewards)) if rewards else np.nan,
        "episode_reward_mean": float(np.mean(rewards)) if rewards else np.nan,
        "episode_len_mean": float(np.mean(lengths)) if lengths else np.nan,
        "episodes_this_iter": len(episodes),
        "policy_reward_mean": {
            pid: float(np.mean(rs)) for pid, rs in policy_rewards.items()
        },
    }
    # user scalars recorded by callbacks: mean/min/max per key
    # (reference metrics.py custom-metrics aggregation)
    custom: Dict[str, List[float]] = {}
    for e in episodes:
        for k, v in getattr(e, "custom_metrics", {}).items():
            custom.setdefault(k, []).append(float(v))
    if custom:
        out["custom_metrics"] = {}
        for k, vals in custom.items():
            out["custom_metrics"][f"{k}_mean"] = float(np.mean(vals))
            out["custom_metrics"][f"{k}_min"] = float(np.min(vals))
            out["custom_metrics"][f"{k}_max"] = float(np.max(vals))
    return out
