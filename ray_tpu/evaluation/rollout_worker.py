"""RolloutWorker: env + policy + sampler, runnable locally or as an actor.

Counterpart of the reference's ``rllib/evaluation/rollout_worker.py:130``
(``sample :824``, ``learn_on_batch :929``, ``get_weights :1578``,
``set_weights :1616``). The same class is the driver-local learner worker
(policy on the TPU mesh) and the remote CPU rollout actor (policy jitted on
host CPU) — platform selection happens naturally because actor processes pin
``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.sample_batch import (
    DEFAULT_POLICY_ID,
    MultiAgentBatch,
    SampleBatch,
)
from ray_tpu.env.env_context import EnvContext
from ray_tpu.env.multi_agent_env import MultiAgentEnv
from ray_tpu.env.registry import get_env_creator
from ray_tpu.env.vector_env import VectorEnv
from ray_tpu.evaluation.sampler import SyncSampler
from ray_tpu.models.catalog import ModelCatalog
from ray_tpu.util import tracing
from ray_tpu.utils.filter import get_filter


class RolloutWorker:
    def __init__(
        self,
        *,
        env_creator: Optional[Callable] = None,
        policy_cls=None,
        policy_specs: Optional[Dict] = None,
        policy_mapping_fn: Optional[Callable] = None,
        config: Optional[Dict] = None,
        worker_index: int = 0,
        num_workers: int = 0,
        seed: Optional[int] = None,
    ):
        self.config = dict(config or {})
        self.worker_index = worker_index
        self.num_workers = num_workers
        self.global_vars: Dict[str, Any] = {"timestep": 0}
        # chaos harness (docs/resilience.md): None unless the config /
        # RAY_TPU_FAULTS arms faults for this process — zero cost when
        # inert
        from ray_tpu.resilience import faults as faults_lib

        self._fault_injector = faults_lib.from_config(self.config)
        self._num_sample_calls = 0

        env_config = EnvContext(
            self.config.get("env_config") or {},
            worker_index=worker_index,
            num_workers=num_workers,
        )
        seed = (
            seed
            if seed is not None
            else self.config.get("seed")
        )
        if seed is not None:
            seed = seed + worker_index * 1000
            # the one sanctioned global-stream touch: third-party envs
            # (gym classics) draw from np.random at reset/step, and
            # per-worker reproducibility requires seeding that stream
            # here; library code itself threads explicit generators
            # ray-tpu: allow[RTA004] global seed side door for third-party envs
            np.random.seed(seed)

        # ---- build env ----
        self.env = None
        self.vector_env = None
        self.preprocessor = None
        if env_creator is not None:
            num_envs = int(self.config.get("num_envs_per_worker", 1))

            def make_sub_env(vector_index):
                ctx = env_config.copy_with_overrides(
                    vector_index=vector_index
                )
                return env_creator(ctx)

            probe = make_sub_env(0)
            from ray_tpu.env.jax_env import (
                JaxVectorEnv,
                JaxVectorEnvAdapter,
            )

            if isinstance(probe, JaxVectorEnv):
                # JAX-native env on the host (actor) lane: ONE adapter
                # drives all sub-env slots through jitted vmapped
                # step/reset — the same pure functions the device
                # rollout lane scans over, so the two lanes share
                # dynamics and per-env key streams (docs/pipeline.md)
                self._multiagent_env = False
                self.env = probe
                self.vector_env = JaxVectorEnvAdapter(
                    probe, num_envs, seed=seed
                )
            elif isinstance(probe, MultiAgentEnv):
                self.env = probe
                self._multiagent_env = True
            else:
                self._multiagent_env = False
                self.env = probe
                envs = [probe] + [
                    make_sub_env(i) for i in range(1, num_envs)
                ]
                self.vector_env = VectorEnv.vectorize_gym_envs(
                    lambda i: envs[i], num_envs, seed=seed
                )

        # ---- policies ----
        self.policy_map: Dict[str, Any] = {}
        self.policy_mapping_fn = policy_mapping_fn or (
            lambda agent_id, **kw: DEFAULT_POLICY_ID
        )
        self.filters: Dict[str, Any] = {}

        if policy_specs is None and policy_cls is not None:
            obs_space = self.config.get("observation_space") or (
                self.env.observation_space
            )
            act_space = self.config.get("action_space") or (
                self.env.action_space
            )
            policy_specs = {
                DEFAULT_POLICY_ID: (policy_cls, obs_space, act_space, {})
            }

        for pid, (cls, obs_space, act_space, overrides) in (
            policy_specs or {}
        ).items():
            pol_config = {
                **self.config,
                **(overrides or {}),
                "worker_index": worker_index,
                "num_workers": num_workers,
            }
            prep = ModelCatalog.get_preprocessor_for_space(obs_space)
            eff_obs_space = prep.observation_space
            if pid == DEFAULT_POLICY_ID or self.preprocessor is None:
                self.preprocessor = prep
            # Rollout workers (worker_index > 0) keep single-device CPU
            # meshes; the local worker builds its learner mesh from config.
            if worker_index > 0:
                pol_config.pop("_mesh", None)
            self.policy_map[pid] = cls(eff_obs_space, act_space, pol_config)
            self.filters[pid] = get_filter(
                self.config.get("observation_filter", "NoFilter"),
                eff_obs_space.shape,
            )

        # ---- input reader (external envs / policy server) ----
        # config["input"] may be a callable(ioctx) -> reader with a
        # .next() method (reference offline/io_context + the
        # PolicyServerInput wiring); strings are offline paths handled
        # by the offline algorithms.
        self.input_reader = None
        inp = self.config.get("input")
        if callable(inp):
            from types import SimpleNamespace

            self.input_reader = inp(
                SimpleNamespace(
                    worker=self,
                    config=self.config,
                    worker_index=worker_index,
                )
            )

        # ---- sampler ----
        self.sampler = None
        if (
            self.input_reader is None
            and self.vector_env is not None
            and self.policy_map
        ):
            pid = DEFAULT_POLICY_ID
            sampler_cls = SyncSampler
            from ray_tpu.evaluation.sampler import AsyncSampler

            if self.config.get("sample_async"):
                sampler_cls = AsyncSampler
            cb_cls = self.config.get("callbacks_class")
            self.callbacks = cb_cls() if cb_cls else None
            self.sampler = sampler_cls(
                vector_env=self.vector_env,
                policy=self.policy_map[pid],
                callbacks=self.callbacks,
                preprocessor=self.preprocessor,
                obs_filter=self.filters.get(pid),
                rollout_fragment_length=int(
                    self.config.get("rollout_fragment_length", 200)
                ),
                batch_mode=self.config.get(
                    "batch_mode", "truncate_episodes"
                ),
                episode_horizon=self.config.get("horizon"),
                clip_actions=self.config.get("clip_actions", False),
                normalize_actions=self.config.get(
                    "normalize_actions", True
                ),
                flush_on_episode_end=not self.config.get(
                    "_fixed_unrolls", False
                ),
            )
        elif env_creator is not None and self._multiagent_env:
            from ray_tpu.evaluation.multi_agent_sampler import (
                MultiAgentSyncSampler,
            )

            self.sampler = MultiAgentSyncSampler(
                env=self.env,
                policy_map=self.policy_map,
                policy_mapping_fn=self.policy_mapping_fn,
                preprocessors={
                    pid: ModelCatalog.get_preprocessor_for_space(
                        p.observation_space
                    )
                    for pid, p in self.policy_map.items()
                },
                obs_filters=self.filters,
                rollout_fragment_length=int(
                    self.config.get("rollout_fragment_length", 200)
                ),
                batch_mode=self.config.get(
                    "batch_mode", "truncate_episodes"
                ),
            )

    # -- sampling --------------------------------------------------------

    def sample(self):
        """reference rollout_worker.py:824 (+ the output-writer wiring
        of reference offline/output_writer.py: every sampled batch is
        mirrored to the configured offline store)."""
        self._num_sample_calls += 1
        if self._fault_injector is not None:
            # deterministic chaos: may delay this call, or hard-exit
            # the process (exactly like a preemption — no exception,
            # no cleanup, the driver sees an actor-death error)
            self._fault_injector.on_sample(
                self.worker_index, self._num_sample_calls
            )
        with tracing.start_span(
            "rollout:sample", worker_index=self.worker_index
        ) as span:
            if self.input_reader is not None:
                batch = self.input_reader.next()
            else:
                assert self.sampler is not None, "worker has no env"
                batch = self.sampler.sample()
            span.set_attribute("env_steps", int(batch.env_steps()))
        out = self.config.get("output")
        if out:
            if not hasattr(self, "_output_writer"):
                from ray_tpu.offline import JsonWriter

                self._output_writer = JsonWriter(
                    out,
                    max_file_size=int(
                        self.config.get(
                            "output_max_file_size", 64 * 1024 * 1024
                        )
                    ),
                )
            self._output_writer.write(batch)
        return batch

    def sample_with_count(self):
        batch = self.sample()
        return batch, batch.env_steps()

    # -- preemption / drain protocol (docs/resilience.md) ----------------

    def preemption_notice(self) -> Optional[float]:
        """Seconds of grace left before this worker's preemption kills
        the process, or None. The FleetController polls this off the
        critical path. Two sources: the injected chaos deadline, and —
        absent an injector notice — the provider stub
        (``resilience/provider_notice.py``: env var / file probe, the
        same surface serving replicas poll), which is where a real
        cloud eviction endpoint plugs in."""
        if self._fault_injector is not None:
            grace = self._fault_injector.preemption_notice()
            if grace is not None:
                return grace
        from ray_tpu.resilience import provider_notice

        return provider_notice.probe()

    def drain_for_preemption(self) -> Dict[str, Any]:
        """Graceful exit: ship everything the fleet would otherwise
        lose with this worker — flushed observation-filter deltas and
        the episodes not yet harvested. Actor calls execute in order,
        so by the time this returns every previously submitted
        ``sample`` has completed and its result is already in the
        object store (the manager harvests those normally). After the
        drain the worker answers no more sample calls usefully; the
        driver removes it from rotation and reaps the process."""
        self._draining = True
        return {
            "filters": self.get_filters(flush_after=True),
            "metrics": self.get_metrics(),
            "num_sample_calls": self._num_sample_calls,
        }

    def add_policy(
        self,
        policy_id: str,
        policy_cls,
        observation_space,
        action_space,
        config_overrides: Optional[Dict] = None,
        weights=None,
    ) -> None:
        """Add a policy at runtime (reference Algorithm.add_policy →
        rollout_worker add_policy; league builders snapshot into the
        live policy map this way)."""
        pol_config = {
            **self.config,
            **(config_overrides or {}),
            "worker_index": self.worker_index,
            "num_workers": self.num_workers,
        }
        if self.worker_index > 0:
            pol_config.pop("_mesh", None)
        prep = ModelCatalog.get_preprocessor_for_space(
            observation_space
        )
        self.policy_map[policy_id] = policy_cls(
            prep.observation_space, action_space, pol_config
        )
        self.filters[policy_id] = get_filter(
            self.config.get("observation_filter", "NoFilter"),
            prep.observation_space.shape,
        )
        if weights is not None:
            self.policy_map[policy_id].set_weights(weights)

    def set_policy_mapping_fn(self, fn: Callable) -> None:
        """Swap the mapping fn; takes effect at the NEXT episode reset
        (the sampler re-consults it per episode) — remapping agents
        mid-episode would train a trajectory's tail under a policy
        that didn't produce its ACTION_LOGP/VF_PREDS."""
        self.policy_mapping_fn = fn
        if self.sampler is not None and hasattr(
            self.sampler, "policy_mapping_fn"
        ):
            self.sampler.policy_mapping_fn = fn

    def get_metrics(self) -> List:
        if self.input_reader is not None and hasattr(
            self.input_reader, "get_metrics"
        ):
            return self.input_reader.get_metrics()
        return self.sampler.get_metrics() if self.sampler else []

    # -- learning --------------------------------------------------------

    def policy(self, pid: str = DEFAULT_POLICY_ID):
        return self.policy_map[pid]

    def learn_on_batch(self, samples) -> Dict:
        """reference rollout_worker.py:929. Policies outside
        config["policies_to_train"] (league opponents, frozen experts)
        are skipped."""
        to_train = self.config.get("policies_to_train")
        if isinstance(samples, MultiAgentBatch):
            info = {}
            for pid, batch in samples.policy_batches.items():
                if pid in self.policy_map and (
                    to_train is None or pid in to_train
                ):
                    info[pid] = self.policy_map[pid].learn_on_batch(batch)
            return info
        return {
            DEFAULT_POLICY_ID: self.policy_map[
                DEFAULT_POLICY_ID
            ].learn_on_batch(samples)
        }

    def compute_gradients(self, samples):
        if isinstance(samples, MultiAgentBatch):
            samples = samples.policy_batches[DEFAULT_POLICY_ID]
        return self.policy_map[DEFAULT_POLICY_ID].compute_gradients(samples)

    # -- DD-PPO worker-side learning (reference ddppo.py:331
    # _sample_and_train_torch_distributed, split into the sample/grad
    # phases the driver-mediated allreduce loop drives) ----------------

    def sample_and_hold(self) -> int:
        """Sample + postprocess a batch and keep it locally for the
        decentralized SGD epochs; returns env steps collected."""
        batch = self.sample()
        if isinstance(batch, MultiAgentBatch):
            batch = batch.policy_batches[DEFAULT_POLICY_ID]
        if SampleBatch.ADVANTAGES in batch:
            adv = np.asarray(
                batch[SampleBatch.ADVANTAGES], np.float32
            )
            batch[SampleBatch.ADVANTAGES] = (
                (adv - adv.mean()) / max(1e-4, adv.std())
            ).astype(np.float32)
        self._held_batch = batch
        return batch.env_steps()

    def grads_on_held_batch(self):
        """One gradient over the locally held batch (one decentralized
        SGD epoch; the driver allreduces across workers). A restarted
        actor has no held batch — resample rather than crash the run."""
        if getattr(self, "_held_batch", None) is None:
            self.sample_and_hold()
        return self.policy_map[DEFAULT_POLICY_ID].compute_gradients(
            self._held_batch
        )

    def apply_gradients(self, grads) -> None:
        self.policy_map[DEFAULT_POLICY_ID].apply_gradients(grads)

    # -- weights & filters ----------------------------------------------

    def get_weights(
        self,
        policies: Optional[List[str]] = None,
        inference_only: bool = False,
    ) -> Dict:
        return {
            pid: (
                p.get_inference_weights()
                if inference_only
                else p.get_weights()
            )
            for pid, p in self.policy_map.items()
            if policies is None or pid in policies
        }

    def set_weights(self, weights: Dict, global_vars: Optional[Dict] = None):
        for pid, w in weights.items():
            if pid in self.policy_map:
                self.policy_map[pid].set_weights(w)
        if global_vars:
            self.set_global_vars(global_vars)

    def get_filters(self, flush_after: bool = False) -> Dict:
        out = {
            pid: f.as_serializable() for pid, f in self.filters.items()
        }
        if flush_after:
            for f in self.filters.values():
                f.clear_buffer()
        return out

    def sync_filters(self, new_filters: Dict) -> None:
        for pid, f in new_filters.items():
            if pid in self.filters:
                self.filters[pid].sync(f)

    def set_global_vars(self, global_vars: Dict) -> None:
        self.global_vars.update(global_vars)
        for p in self.policy_map.values():
            p.on_global_var_update(global_vars)

    # -- state / misc ----------------------------------------------------

    def save(self) -> Dict:
        return {
            "policy_states": {
                pid: p.get_state() for pid, p in self.policy_map.items()
            },
            "filters": self.get_filters(),
        }

    def restore(self, state: Dict) -> None:
        for pid, s in state.get("policy_states", {}).items():
            if pid in self.policy_map:
                self.policy_map[pid].set_state(s)
        self.sync_filters(state.get("filters", {}))

    def apply(self, fn: Callable, *args, **kwargs):
        """reference rollout_worker.py apply (used by foreach_worker)."""
        return fn(self, *args, **kwargs)

    def foreach_env(self, fn: Callable) -> List:
        if self.vector_env is None:
            return [fn(self.env)] if self.env else []
        return [fn(e) for e in self.vector_env.get_sub_environments()]

    def foreach_policy(self, fn: Callable) -> List:
        return [fn(p, pid) for pid, p in self.policy_map.items()]

    def stop(self) -> None:
        # stop the async sampling thread BEFORE closing its envs
        if self.sampler is not None and hasattr(self.sampler, "stop"):
            try:
                self.sampler.stop()
            except Exception:
                pass
        if self.input_reader is not None and hasattr(
            self.input_reader, "shutdown"
        ):
            try:
                self.input_reader.shutdown()
            except Exception:
                pass
        if self.vector_env is not None:
            for e in self.vector_env.get_sub_environments():
                try:
                    e.close()
                except Exception:
                    pass

    def ping(self) -> str:
        return "pong"
