"""Distributed span tracing across task/actor boundaries.

Counterpart of the reference's OpenTelemetry integration
(``python/ray/util/tracing/tracing_helper.py``: every remote
function/actor method is wrapped with span-propagating proxies,
``_inject_tracing_into_function :324``, ``_inject_tracing_into_class
:449``). Same shape without the OTel dependency: when tracing is
enabled, submissions carry a trace context (trace_id + parent span
id), workers open a child span around execution — user code can open
nested spans via :func:`start_span` and they parent correctly — and
finished spans ride back on the result message into the driver's
tracer, exportable as a span list or a chrome://tracing file.

Usage::

    from ray_tpu.util import tracing
    tracing.enable()
    with tracing.start_span("rollout-phase"):
        ray.get(worker.sample.remote())   # worker span is a child
    spans = tracing.get_spans()
    tracing.export_chrome_trace("/tmp/trace.json")

Enable for every process with ``RAY_TPU_TRACE=1`` (workers inherit the
env), or per-driver with :func:`enable`.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_enabled = os.environ.get("RAY_TPU_TRACE") == "1"
_current: contextvars.ContextVar[Optional["Span"]] = (
    contextvars.ContextVar("ray_tpu_span", default=None)
)
_finished: List[Dict] = []
_lock = threading.Lock()
# bound the span buffer: long-running jobs must not grow driver memory
# monotonically — oldest spans drop first (export/inspect regularly,
# or raise via RAY_TPU_TRACE_BUFFER)
_MAX_SPANS = int(os.environ.get("RAY_TPU_TRACE_BUFFER", 100_000))


def _append_bounded(records: List[Dict]) -> None:
    with _lock:
        _finished.extend(records)
        if len(_finished) > _MAX_SPANS:
            del _finished[: len(_finished) - _MAX_SPANS]


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


class Span:
    __slots__ = (
        "trace_id",
        "span_id",
        "parent_id",
        "name",
        "start",
        "end",
        "attributes",
        "process",
        "thread",
        "thread_name",
    )

    def __init__(self, name: str, trace_id=None, parent_id=None):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self.end: Optional[float] = None
        self.attributes: Dict[str, Any] = {}
        self.process = os.getpid()
        # thread identity so prefetcher/feeder/learner threads render
        # as separate chrome-trace lanes instead of one flat tid 0
        t = threading.current_thread()
        self.thread = t.ident or 0
        self.thread_name = t.name

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def finish(self, end: Optional[float] = None) -> Dict:
        self.end = time.time() if end is None else end
        record = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "attributes": dict(self.attributes),
            "pid": self.process,
            "tid": self.thread,
            "thread_name": self.thread_name,
        }
        if _enabled:  # disabled tracing records nothing
            _append_bounded([record])
        return record


class _NullSpan:
    """Returned by start_span when tracing is off: every operation is a
    no-op, so the disabled hot path costs one flag check (no uuid, no
    clock reads, no allocation)."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None

    def set_attribute(self, key: str, value: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


@contextlib.contextmanager
def start_span(name: str, **attributes):
    """Open a span under the current one (driver or worker side)."""
    if not _enabled:
        yield _NULL_SPAN
        return
    parent = _current.get()
    span = Span(
        name,
        trace_id=parent.trace_id if parent else None,
        parent_id=parent.span_id if parent else None,
    )
    for k, v in attributes.items():
        span.set_attribute(k, v)
    token = _current.set(span)
    try:
        yield span
    finally:
        _current.reset(token)
        span.finish()


def event(name: str, **attributes) -> None:
    """Record a zero-duration span (dead worker, recompile, ...)
    parented under the current span. No-op when tracing is off."""
    if not _enabled:
        return
    parent = _current.get()
    span = Span(
        name,
        trace_id=parent.trace_id if parent else None,
        parent_id=parent.span_id if parent else None,
    )
    span.attributes.update(attributes)
    span.finish(end=span.start)


def record_span(
    name: str, start: float, end: float, **attributes
) -> None:
    """Record a span whose interval was measured out-of-band (e.g. a
    queue wait that ended when ``get()`` returned). ``start``/``end``
    are ``time.time()`` stamps. No-op when tracing is off."""
    if not _enabled:
        return
    parent = _current.get()
    span = Span(
        name,
        trace_id=parent.trace_id if parent else None,
        parent_id=parent.span_id if parent else None,
    )
    span.start = start
    span.attributes.update(attributes)
    span.finish(end=end)


def get_current_span() -> Optional[Span]:
    return _current.get()


# -- boundary plumbing (called by core/api.py and core/worker_proc.py) --


def inject_context() -> Optional[Dict]:
    """Driver-side: the context a submission carries
    (tracing_helper's span injection role)."""
    if not _enabled:
        return None
    parent = _current.get()
    if parent is not None:
        return {
            "trace_id": parent.trace_id,
            "parent_span_id": parent.span_id,
        }
    return {"trace_id": uuid.uuid4().hex[:16], "parent_span_id": None}


@contextlib.contextmanager
def remote_span(ctx: Optional[Dict], name: str):
    """Worker-side: execution span as a child of the submitted
    context; no-op when the submission carried none. A present
    context IS the worker's enable signal (the driver's enable() flag
    doesn't cross the process boundary; the injected context does),
    so nested user spans inside the execution record too."""
    global _enabled
    if ctx is None:
        yield None
        return
    span = Span(
        name,
        trace_id=ctx.get("trace_id"),
        parent_id=ctx.get("parent_span_id"),
    )
    token = _current.set(span)
    was_enabled = _enabled
    _enabled = True
    try:
        yield span
    finally:
        _current.reset(token)
        span.finish()
        _enabled = was_enabled


@contextlib.contextmanager
def context_span(ctx: Optional[Dict], name: str, **attributes):
    """Open a span under an EXPLICIT trace context (the serving path's
    ``x-ray-tpu-trace`` propagation: ingress → router → replica spans
    stitch into one trace even though they run on different threads,
    where contextvars can't carry the parent). Unlike
    :func:`remote_span` this never force-enables tracing — when the
    process has tracing off it costs one flag check and yields the
    null span, so it is safe on the serve hot path. ``ctx`` is an
    :func:`inject_context`-shaped dict; ``None`` falls back to the
    calling context's current span (plain :func:`start_span`
    semantics)."""
    if not _enabled:
        yield _NULL_SPAN
        return
    if ctx is None:
        with start_span(name, **attributes) as span:
            yield span
        return
    span = Span(
        name,
        trace_id=ctx.get("trace_id"),
        parent_id=ctx.get("parent_span_id"),
    )
    for k, v in attributes.items():
        span.set_attribute(k, v)
    token = _current.set(span)
    try:
        yield span
    finally:
        _current.reset(token)
        span.finish()


def drain_finished() -> List[Dict]:
    """Worker-side: hand finished spans to the result pipe."""
    with _lock:
        out = list(_finished)
        _finished.clear()
    return out


def record_spans(spans: List[Dict]) -> None:
    """Driver-side: absorb spans shipped back from a worker."""
    if not spans:
        return
    _append_bounded(spans)


def get_spans() -> List[Dict]:
    with _lock:
        return list(_finished)


def clear() -> None:
    with _lock:
        _finished.clear()


def _clamped_intervals(spans: List[Dict]) -> Dict[str, tuple]:
    """Per-span [start, end] intervals with cross-actor clock skew
    contained: a child span is clamped into its parent's (clamped)
    interval, and end never precedes start. Worker clocks are plain
    ``time.time()`` — a worker ahead of the driver used to render its
    execution span outside (or "before") the submitting span, which
    chrome://tracing draws as negative-duration garbage. Parentage is
    ground truth (the submission carried the context), so the parent
    interval bounds the child."""
    by_id = {
        s["span_id"]: s for s in spans if s.get("span_id")
    }
    out: Dict[str, tuple] = {}

    def resolve(s, seen) -> tuple:
        sid = s.get("span_id")
        if sid in out:
            return out[sid]
        start = s["start"]
        end = s["end"] if s["end"] is not None else start
        end = max(end, start)
        pid = s.get("parent_id")
        parent = by_id.get(pid)
        if parent is not None and pid not in seen:
            ps, pe = resolve(parent, seen | {pid})
            start = min(max(start, ps), pe)
            end = min(max(end, start), pe)
        if sid:
            out[sid] = (start, end)
        return (start, end)

    for s in spans:
        resolve(s, {s.get("span_id")})
    return out


def export_chrome_trace(
    path: str, since: Optional[float] = None
) -> str:
    """chrome://tracing JSON (the reference's ray.timeline format,
    _private/state.py:435, with span parent/trace ids attached).
    ``since`` keeps only spans that END at or after that
    ``time.time()`` stamp (Algorithm.export_timeline's last-N-iteration
    window). Each (pid, tid) lane carries a thread_name metadata event
    so prefetcher/feeder/learner threads are labeled in the viewer.
    Child spans are clamped into their parent's interval so cross-actor
    clock skew can't produce negative durations or out-of-parent
    rendering (raw stamps stay available in the span list API)."""
    with _lock:
        spans = list(_finished)
    if since is not None:
        spans = [
            s for s in spans if (s["end"] or s["start"]) >= since
        ]
    clamped = _clamped_intervals(spans)
    events = []
    for s in spans:
        start, end = clamped.get(
            s.get("span_id"),
            (s["start"], s["end"] or s["start"]),
        )
        events.append(
            {
                "name": s["name"],
                "cat": "span",
                "ph": "X",
                "ts": start * 1e6,
                "dur": (end - start) * 1e6,
                "pid": s["pid"],
                "tid": s.get("tid", 0),
                "args": {
                    "trace_id": s["trace_id"],
                    "span_id": s["span_id"],
                    "parent_id": s["parent_id"],
                    **s["attributes"],
                },
            }
        )
    lanes = {}
    for s in spans:
        lanes.setdefault(
            (s["pid"], s.get("tid", 0)), s.get("thread_name")
        )
    for (pid, tid), tname in sorted(lanes.items()):
        if tname:
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path
