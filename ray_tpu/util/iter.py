"""ParallelIterator: sharded lazy iterators over the actor fleet.

Counterpart of the reference's ``python/ray/util/iter.py``
(``ParallelIterator :132``, ``LocalIterator :705``, ``from_actors
:114``, ``gather_async :520``) — the legacy distributed-iterator API
its execution plans were built on. The shape survives unchanged here:
each shard is an actor holding its iterator state, transforms
(``for_each``/``filter``/``batch``/``flatten``) accumulate lazily and
execute inside the shard actor, and ``gather_sync``/``gather_async``
fold the shards into a driver-side :class:`LocalIterator` (round-robin
vs completion order). TPU disposition: the LEARNER side of the old
execution plans is the jitted SGD nest; this module serves the
data-movement half (rollout streams, offline shards) and API parity.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, List, Optional

import ray_tpu as ray

_SENTINEL = "__parallel_iterator_stop__"


@ray.remote
class _ShardActor:
    """One shard: owns an item stream + the accumulated transforms."""

    def __init__(self, make_iter, transforms):
        self._it = iter(make_iter())
        self._transforms = list(transforms)

    def set_transforms(self, transforms):
        self._transforms = list(transforms)
        return True

    def par_iter_next(self):
        while True:
            try:
                item = next(self._it)
            except StopIteration:
                return _SENTINEL
            out = self._apply(item)
            if out is not _SENTINEL:
                return out

    def _apply(self, item):
        for kind, fn in self._transforms:
            if kind == "for_each":
                item = fn(item)
            elif kind == "filter":
                if not fn(item):
                    return _SENTINEL
            elif kind == "batch":
                buf = [item]
                while len(buf) < fn:
                    try:
                        nxt = next(self._it)
                    except StopIteration:
                        break
                    buf.append(nxt)
                item = buf
            elif kind == "flatten":
                # flatten re-enters the stream: push extras back
                items = list(item)
                if not items:
                    return _SENTINEL
                rest = items[1:]
                if rest:
                    it = self._it

                    def chained(rest=rest, it=it):
                        yield from rest
                        yield from it

                    self._it = chained()
                item = items[0]
        return item


class _ActorShard:
    """Adapter for ``from_actors``: the actor supplies items via its
    own ``par_iter_next`` (reference ParallelIteratorWorker)."""

    def __init__(self, actor, method: str):
        self._actor = actor
        self._method = method

    def next_ref(self):
        return getattr(self._actor, self._method).remote()


class ParallelIterator:
    """reference util/iter.py:132 (scoped: the documented surface)."""

    def __init__(self, shards: List, transforms=None, name="it"):
        self._shards = shards
        self._transforms = list(transforms or [])
        self._name = name

    # -- construction ----------------------------------------------------

    @staticmethod
    def from_items(
        items: List, num_shards: int = 2, repeat: bool = False
    ) -> "ParallelIterator":
        chunks = [items[i::num_shards] for i in range(num_shards)]

        def mk(chunk):
            def gen():
                while True:
                    yield from chunk
                    if not repeat:
                        return

            return gen

        return ParallelIterator(
            [_ShardActor.remote(mk(c), []) for c in chunks],
            name="from_items",
        )

    @staticmethod
    def from_range(
        n: int, num_shards: int = 2, repeat: bool = False
    ) -> "ParallelIterator":
        return ParallelIterator.from_items(
            list(builtins.range(n)), num_shards, repeat
        )

    @staticmethod
    def from_iterators(
        generators: List[Callable], repeat: bool = False
    ) -> "ParallelIterator":
        def mk(g):
            def gen():
                while True:
                    yield from g()
                    if not repeat:
                        return

            return gen

        return ParallelIterator(
            [_ShardActor.remote(mk(g), []) for g in generators],
            name="from_iterators",
        )

    @staticmethod
    def from_actors(
        actors: List, method: str = "par_iter_next"
    ) -> "ParallelIterator":
        """Iterate items an existing actor fleet produces (reference
        from_actors :114; actors implement ``par_iter_next``)."""
        return ParallelIterator(
            [_ActorShard(a, method) for a in actors],
            name="from_actors",
        )

    # -- transforms (lazy; run inside the shard) -------------------------

    def _with(self, kind, fn) -> "ParallelIterator":
        return ParallelIterator(
            self._shards,
            self._transforms + [(kind, fn)],
            name=f"{self._name}.{kind}",
        )

    def for_each(self, fn: Callable) -> "ParallelIterator":
        return self._with("for_each", fn)

    def filter(self, fn: Callable) -> "ParallelIterator":
        return self._with("filter", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._with("batch", n)

    def flatten(self) -> "ParallelIterator":
        return self._with("flatten", None)

    def combine(self, fn: Callable) -> "ParallelIterator":
        return self._with("for_each", fn)._with("flatten", None)

    # -- gathering -------------------------------------------------------

    def num_shards(self) -> int:
        return len(self._shards)

    def shards(self) -> List["LocalIterator"]:
        return [
            LocalIterator(
                _shard_stream([s], self._transforms, ordered=True)
            )
            for s in self._shards
        ]

    def gather_sync(self) -> "LocalIterator":
        """Round-robin over shards (deterministic order, blocks on the
        slowest shard — reference gather_sync)."""
        return LocalIterator(
            _shard_stream(
                self._shards, self._transforms, ordered=True
            )
        )

    def gather_async(self, num_async: int = 1) -> "LocalIterator":
        """Completion order: every shard keeps ``num_async`` fetches in
        flight; items yield as they land (reference gather_async
        :520)."""
        return LocalIterator(
            _shard_stream(
                self._shards,
                self._transforms,
                ordered=False,
                num_async=num_async,
            )
        )

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        if self._transforms or other._transforms:
            raise ValueError(
                "union requires untransformed iterators (apply "
                "transforms after union)"
            )
        return ParallelIterator(
            self._shards + other._shards, name="union"
        )

    def take(self, n: int) -> List:
        return self.gather_sync().take(n)

    def show(self, n: int = 20) -> None:
        for x in self.take(n):
            print(x)

    def __repr__(self):
        return f"ParallelIterator[{self._name}, shards={len(self._shards)}]"


def _apply_local(item, transforms, stream_state):
    for kind, fn in transforms:
        if kind == "for_each":
            item = fn(item)
        elif kind == "filter":
            if not fn(item):
                return _SENTINEL
        elif kind == "batch":
            buf = stream_state.setdefault("batch_buf", [])
            buf.append(item)
            if len(buf) < fn:
                return _SENTINEL
            item = list(buf)
            buf.clear()
        elif kind == "flatten":
            pending = stream_state.setdefault("flat_buf", [])
            pending.extend(item)
            if not pending:
                return _SENTINEL
            item = pending.pop(0)
            # remaining flattened items re-enter via stream_state —
            # drained by the caller before fetching the next item
    return item


def _shard_stream(shards, transforms, ordered: bool, num_async: int = 1):
    """Generator over shard items; transforms apply shard-side for
    _ShardActor shards (pushed at first use) and driver-side for
    actor-backed shards gathered via from_actors."""

    if transforms and any(
        isinstance(s, _ActorShard) for s in shards
    ):
        raise ValueError(
            "transforms on from_actors iterators run driver-side: "
            "gather first, then for_each on the LocalIterator"
        )

    def next_ref(s):
        if isinstance(s, _ActorShard):
            return s.next_ref()
        return s.par_iter_next.remote()

    live = list(shards)
    state = {}
    # push transforms into _ShardActor shards once (their _apply runs
    # in-actor); from_actors shards have none (enforced above)
    pushed = set()
    for s in live:
        if not isinstance(s, _ActorShard) and transforms and (
            id(s) not in pushed
        ):
            ray.get(s.set_transforms.remote(list(transforms)))
            pushed.add(id(s))
    if ordered:
        idx = 0
        while live:
            s = live[idx % len(live)]
            item = ray.get(next_ref(s))
            if isinstance(item, str) and item == _SENTINEL:
                live.remove(s)
                continue
            idx += 1
            yield item
    else:
        in_flight = {}
        for s in live:
            for _ in range(max(1, num_async)):
                in_flight[next_ref(s)] = s
        while in_flight:
            ready, _ = ray.wait(
                list(in_flight.keys()), num_returns=1, timeout=30.0
            )
            if not ready:
                continue
            ref = ready[0]
            s = in_flight.pop(ref)
            try:
                item = ray.get(ref)
            finally:
                ray.free([ref])
            if isinstance(item, str) and item == _SENTINEL:
                continue  # shard exhausted; stop refilling it
            in_flight[next_ref(s)] = s
            yield item


class LocalIterator:
    """reference util/iter.py:705 — a driver-side iterator with the
    same transform surface."""

    def __init__(self, gen):
        self._gen = iter(gen)
        self._transforms: List = []

    def __iter__(self):
        state: dict = {}
        for item in self._gen:
            out = _apply_local(item, self._transforms, state)
            if out is _SENTINEL:
                continue
            yield out
            # drain flattened leftovers
            pending = state.get("flat_buf")
            while pending:
                yield pending.pop(0)

    def for_each(self, fn: Callable) -> "LocalIterator":
        self._transforms.append(("for_each", fn))
        return self

    def filter(self, fn: Callable) -> "LocalIterator":
        self._transforms.append(("filter", fn))
        return self

    def batch(self, n: int) -> "LocalIterator":
        self._transforms.append(("batch", n))
        return self

    def flatten(self) -> "LocalIterator":
        self._transforms.append(("flatten", None))
        return self

    def take(self, n: int) -> List:
        out: List = []
        for x in self:
            out.append(x)
            if len(out) >= n:
                break
        return out

    def union(self, other: "LocalIterator") -> "LocalIterator":
        def interleave():
            a, b = iter(self), iter(other)
            alive = [a, b]
            while alive:
                for it in list(alive):
                    try:
                        yield next(it)
                    except StopIteration:
                        alive.remove(it)

        return LocalIterator(interleave())


def from_items(items, num_shards: int = 2, repeat: bool = False):
    return ParallelIterator.from_items(items, num_shards, repeat)


def from_range(n, num_shards: int = 2, repeat: bool = False):
    return ParallelIterator.from_range(n, num_shards, repeat)


def from_iterators(generators, repeat: bool = False):
    return ParallelIterator.from_iterators(generators, repeat)


def from_actors(actors, method: str = "par_iter_next"):
    return ParallelIterator.from_actors(actors, method)
