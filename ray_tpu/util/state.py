"""State observability API: programmatic cluster introspection.

Counterpart of the reference's ``ray.util.state`` (``list_actors``,
``list_tasks``, ``list_objects``, ``list_nodes`` — the API behind
``ray list ...``), read straight from the driver runtime the way the
reference reads from the GCS. Each entry is a plain dict, filterable
with ``filters=[(key, "=", value), ...]``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def _apply_filters(rows: List[Dict], filters) -> List[Dict]:
    for key, op, value in filters or ():
        if op == "=":
            rows = [r for r in rows if r.get(key) == value]
        elif op == "!=":
            rows = [r for r in rows if r.get(key) != value]
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return rows


def _runtime():
    from ray_tpu.core import api

    return api._require_runtime()


def list_actors(filters=None) -> List[Dict]:
    """reference state.list_actors: one dict per actor."""
    rt = _runtime()
    with rt.lock:
        rows = [
            {
                "actor_id": rec.actor_id,
                "name": rec.name,
                "state": "DEAD" if rec.dead else "ALIVE",
                "restarts": rec.restarts,
                "pid": (
                    rec.worker.proc.pid if rec.worker.proc else None
                ),
            }
            for rec in rt.actors.values()
        ]
    return _apply_filters(rows, filters)


def list_tasks(filters=None) -> List[Dict]:
    """Pending + in-flight tasks (the reference also lists finished
    ones from the GCS; finished tasks here live in the timeline)."""
    rt = _runtime()
    with rt.lock:
        rows = [
            {
                "task_id": t.task_id,
                "name": t.name,
                "state": "PENDING_SCHEDULING",
                "num_cpus": t.num_cpus,
            }
            for t in rt.pending
        ]
        for w in rt.pool:
            for t in w.inflight.values():
                rows.append(
                    {
                        "task_id": t.task_id,
                        "name": t.name,
                        "state": "RUNNING",
                        "num_cpus": t.num_cpus,
                        "worker_id": w.worker_id,
                    }
                )
    return _apply_filters(rows, filters)


def list_objects(filters=None) -> List[Dict]:
    """Entries in the driver object store."""
    rt = _runtime()
    store = rt.store
    with store._lock:
        rows = [
            {
                "object_id": oid,
                "ready": e.event.is_set(),
                "in_shm": e.shm is not None,
                "spilled": e.spill_path is not None,
                "ref_count": store._refcounts.get(oid, 0),
            }
            for oid, e in store._entries.items()
        ]
    return _apply_filters(rows, filters)


def list_nodes(filters=None) -> List[Dict]:
    """The head plus any joined agent nodes (core/cluster.py)."""
    rt = _runtime()
    rows = [
        {
            "node_id": "head",
            "state": "ALIVE",
            "num_cpus": rt.num_cpus,
        }
    ]
    cluster = getattr(rt, "cluster", None)
    if cluster is not None:
        for nid, node in list(cluster.nodes.items()):
            rows.append(
                {
                    "node_id": nid,
                    "state": "DEAD" if node.dead else "ALIVE",
                    "num_cpus": node.num_cpus,
                }
            )
    return _apply_filters(rows, filters)


def summarize_tasks() -> Dict[str, int]:
    out: Dict[str, int] = {}
    for t in list_tasks():
        out[t["state"]] = out.get(t["state"], 0) + 1
    return out
