"""Distributed Queue: an actor-backed FIFO shared between tasks,
actors, and the driver.

Counterpart of the reference's ``ray/util/queue.py`` Queue — the same
put/get/qsize/empty/full surface (with blocking and timeouts) backed
by a dedicated queue actor, reachable from anywhere a handle can be
pickled to (workers reach it through the worker-API channel).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu as ray


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        from collections import deque

        self.maxsize = maxsize
        self.items = deque()

    def qsize(self) -> int:
        return len(self.items)

    def put(self, item) -> bool:
        """False if full (the CALLER retries/blocks — the actor's
        ordered queue must never park, or every other caller stalls
        behind it)."""
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def get(self):
        if not self.items:
            return False, None
        return True, self.items.popleft()

    def get_batch(self, n: int) -> List:
        out = []
        while self.items and len(out) < n:
            out.append(self.items.popleft())
        return out


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        self.actor = _QueueActor.options(
            **(actor_options or {})
        ).remote(maxsize)

    def qsize(self) -> int:
        return ray.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(
        self,
        item: Any,
        block: bool = True,
        timeout: Optional[float] = None,
    ) -> None:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if ray.get(self.actor.put.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.time() >= deadline:
                raise Full
            time.sleep(0.01)

    def get(
        self, block: bool = True, timeout: Optional[float] = None
    ) -> Any:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            ok, item = ray.get(self.actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.time() >= deadline:
                raise Empty
            time.sleep(0.01)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_batch(self, n: int) -> List:
        """Up to n items in one round trip (drains what is there)."""
        return ray.get(self.actor.get_batch.remote(n))

    def shutdown(self) -> None:
        try:
            ray.kill(self.actor)
        except Exception:
            pass
