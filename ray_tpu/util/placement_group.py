"""Placement groups: gang resource reservation, cluster-wide.

Counterpart of the reference's ``python/ray/util/placement_group.py:32``
(PlacementGroup, ``placement_group() :126``) and the raylet-side 2PC
bundle reservation (``raylet/placement_group_resource_manager.h`` +
``gcs/gcs_server/gcs_placement_group_manager.cc``): bundles are
assigned to nodes per strategy (PACK / SPREAD / STRICT_PACK /
STRICT_SPREAD) across the head AND registered fleet agents, then
reserved atomically — head CPUs out of the scheduler pool, agent CPUs
out of each node's spillover ledger — with full rollback if any node's
prepare fails. Tasks/actors submitted with
``PlacementGroupSchedulingStrategy`` draw admission from their bundle's
reservation and run ON the bundle's node. On a TPU pod the accelerator
side of gang placement is the jax mesh itself (devices are co-scheduled
by construction); this covers the CPU-fleet side."""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional

_HEAD = "__head__"


class PlacementGroup:
    """reference placement_group.py:32."""

    def __init__(self, bundles: List[Dict[str, float]], strategy: str,
                 name: str = ""):
        self.id = uuid.uuid4().hex[:16]
        self.bundles = [dict(b) for b in bundles]
        self.strategy = strategy
        self.name = name
        self._lock = threading.Lock()
        self._reserved = False
        self._removed = False
        self._ready_event = threading.Event()
        # per-bundle used CPUs (admission control inside the group)
        self._bundle_used = [0.0] * len(bundles)
        # per-bundle host: None = head, else the agent node_id
        self.bundle_nodes: List[Optional[str]] = [None] * len(bundles)
        self._head_reserved = 0.0
        self._reserved_node_ids: List[str] = []

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def total_cpus(self) -> float:
        return float(sum(b.get("CPU", 0.0) for b in self.bundles))

    # -- reservation against the runtime ----------------------------------

    def _assign_bundles(self, offers) -> Optional[List[str]]:
        """Map each bundle to a node key given ``offers`` =
        [(node_key, free_cpus)] with the head first. Returns None when
        the strategy cannot be satisfied right now."""
        needs = [b.get("CPU", 0.0) for b in self.bundles]
        free = {k: f for k, f in offers}
        keys = [k for k, _ in offers]
        if self.strategy in ("PACK", "STRICT_PACK"):
            total = sum(needs)
            for k in keys:  # head first, then agents
                if free[k] + 1e-9 >= total:
                    return [k] * len(needs)
            if self.strategy == "STRICT_PACK":
                return None
            # PACK fallback: fewest nodes greedily (first-fit in
            # descending-capacity order after the head)
            order = [keys[0]] + sorted(
                keys[1:], key=lambda k: -free[k]
            )
            assign = []
            for need in needs:
                for k in order:
                    if free[k] + 1e-9 >= need:
                        free[k] -= need
                        assign.append(k)
                        break
                else:
                    return None
            return assign
        # SPREAD / STRICT_SPREAD: one bundle per distinct node while
        # nodes remain; plain SPREAD reuses nodes best-effort after
        assign: List[Optional[str]] = [None] * len(needs)
        used = set()
        for i, need in enumerate(needs):
            cand = None
            for k in keys:
                if k not in used and free[k] + 1e-9 >= need:
                    cand = k
                    break
            if cand is None:
                if self.strategy == "STRICT_SPREAD":
                    return None
                cand = max(free, key=lambda k: free[k])
                if free[cand] + 1e-9 < need:
                    return None
            used.add(cand)
            free[cand] -= need
            assign[i] = cand
        return assign

    def _try_reserve(self, rt) -> bool:
        """Two-phase reserve across head + agents: assign bundles
        against a capacity snapshot, commit head share under the
        runtime lock, then prepare each agent's ledger — rolling back
        everything if any node refuses (the raylet 2PC's
        PREPARE/COMMIT, in-process)."""
        cluster = getattr(rt, "cluster", None)
        nodes = []
        if cluster is not None:
            nodes = [
                n for n in cluster.nodes.values() if not n.dead
            ]
        with rt.lock:
            head_free = rt.available_cpus
        offers = [(_HEAD, head_free)] + [
            (n.node_id, n.free_cpus()) for n in nodes
        ]
        assign = self._assign_bundles(offers)
        if assign is None:
            return False
        need_head = sum(
            b.get("CPU", 0.0)
            for b, a in zip(self.bundles, assign)
            if a == _HEAD
        )
        with rt.lock:
            if need_head > rt.available_cpus + 1e-9:
                return False
            rt.available_cpus -= need_head
        reserved = []
        ok = True
        for n in nodes:
            need = sum(
                b.get("CPU", 0.0)
                for b, a in zip(self.bundles, assign)
                if a == n.node_id
            )
            if need <= 0:
                continue
            if n.pg_reserve(self.id, need):
                reserved.append(n)
            else:
                ok = False
                break
        if not ok:  # rollback (a node filled up between offer+prepare)
            with rt.lock:
                rt.available_cpus += need_head
            for n in reserved:
                n.pg_release(self.id)
            return False
        with self._lock:
            self._reserved = True
            self._head_reserved = need_head
            self._reserved_node_ids = [n.node_id for n in reserved]
            self.bundle_nodes = [
                None if a == _HEAD else a for a in assign
            ]
        self._ready_event.set()
        # tasks queued against this group may now be admissible
        rt._dispatch_pending()
        return True

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the bundles are reserved (reference pg.ready()).
        Retries as capacity frees up."""
        from ray_tpu.core.api import _require_runtime

        rt = _require_runtime()
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while not self._ready_event.is_set():
            if self._removed:
                return False
            if self._try_reserve(rt):
                break
            if (
                deadline is not None
                and time.monotonic() >= deadline
            ):
                return False
            time.sleep(0.01)
        return True

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    # -- admission for member tasks (runtime lock held) -------------------

    def _bundle_on(self, i: int, node_id: Optional[str]) -> bool:
        """Is bundle i hosted on ``node_id`` (None = the head)?"""
        return self.bundle_nodes[i] == node_id

    def _fits(
        self,
        num_cpus: float,
        bundle_index: int = -1,
        node_id: Optional[str] = None,
    ) -> bool:
        """Capacity check scoped to bundles living on ``node_id`` —
        the head dispatcher passes None; the spillover path asks per
        agent (a bundle reserved on node X only admits work ON X)."""
        if not self._reserved or self._removed:
            return False
        with self._lock:
            if bundle_index >= 0:
                if not self._bundle_on(bundle_index, node_id):
                    return False
                cap = self.bundles[bundle_index].get("CPU", 0.0)
                return (
                    self._bundle_used[bundle_index] + num_cpus
                    <= cap + 1e-9
                )
            for i, b in enumerate(self.bundles):
                if self._bundle_on(i, node_id) and (
                    self._bundle_used[i] + num_cpus
                    <= b.get("CPU", 0.0) + 1e-9
                ):
                    return True
            return False

    def _acquire(
        self,
        num_cpus: float,
        bundle_index: int = -1,
        node_id: Optional[str] = None,
    ) -> int:
        """→ the bundle index actually charged (the admission record
        releases exactly this bundle later). -1 if nothing fits —
        including an explicit bundle_index whose capacity was taken
        between the caller's _fits and this charge (actor creations
        race the dispatcher on the group's own lock)."""
        with self._lock:
            if bundle_index < 0:
                for i, b in enumerate(self.bundles):
                    if self._bundle_on(i, node_id) and (
                        self._bundle_used[i] + num_cpus
                        <= b.get("CPU", 0.0) + 1e-9
                    ):
                        bundle_index = i
                        break
                else:
                    return -1
            else:
                if not self._bundle_on(bundle_index, node_id) or (
                    self._bundle_used[bundle_index] + num_cpus
                    > self.bundles[bundle_index].get("CPU", 0.0)
                    + 1e-9
                ):
                    return -1
            self._bundle_used[bundle_index] += num_cpus
            return bundle_index

    def _acquire_any(self, num_cpus: float, bundle_index: int = -1):
        """Atomically find-and-charge a fitting bundle on ANY node
        (actor placement: the actor goes wherever its bundle lives).
        → (bundle_index, node_id) or (-1, None)."""
        with self._lock:
            if self._removed or not self._reserved:
                return -1, None
            cands = (
                [bundle_index]
                if bundle_index >= 0
                else range(len(self.bundles))
            )
            for i in cands:
                if (
                    self._bundle_used[i] + num_cpus
                    <= self.bundles[i].get("CPU", 0.0) + 1e-9
                ):
                    self._bundle_used[i] += num_cpus
                    return i, self.bundle_nodes[i]
            return -1, None

    def node_lost(self, node_id: str) -> bool:
        """The host of some bundles died: mark them LOST (they admit
        nothing — "__lost__" matches neither the head's None nor any
        live agent id) so work targeting them fails fast instead of
        queueing forever. → True if this group was affected."""
        with self._lock:
            hit = False
            for i, nid in enumerate(self.bundle_nodes):
                if nid == node_id:
                    self.bundle_nodes[i] = "__lost__"
                    hit = True
            if node_id in self._reserved_node_ids:
                self._reserved_node_ids.remove(node_id)
            return hit

    def has_live_bundle(
        self, num_cpus: float, bundle_index: int = -1
    ) -> bool:
        """Could ``num_cpus`` EVER be admitted given lost bundles
        (ignoring current usage)? False → submitting is a dead end."""
        with self._lock:
            cands = (
                [bundle_index]
                if bundle_index >= 0
                else range(len(self.bundles))
            )
            return any(
                self.bundle_nodes[i] != "__lost__"
                and self.bundles[i].get("CPU", 0.0) + 1e-9
                >= num_cpus
                for i in cands
            )

    def _release(self, num_cpus: float, bundle_index: int) -> None:
        with self._lock:
            if 0 <= bundle_index < len(self._bundle_used):
                self._bundle_used[bundle_index] = max(
                    0.0, self._bundle_used[bundle_index] - num_cpus
                )

    def remove(self) -> None:
        from ray_tpu.core.api import _require_runtime

        if self._removed:
            return
        self._removed = True
        if self._reserved:
            rt = _require_runtime()
            with rt.lock:
                rt.available_cpus += self._head_reserved
            cluster = getattr(rt, "cluster", None)
            if cluster is not None:
                for nid in self._reserved_node_ids:
                    node = cluster.nodes.get(nid)
                    if node is not None:
                        node.pg_release(self.id)
            self._reserved = False
        _GROUPS.pop(self.id, None)

    def __repr__(self):
        return (
            f"PlacementGroup(id={self.id[:8]}, "
            f"bundles={self.bundles}, reserved={self._reserved})"
        )


class PlacementGroupSchedulingStrategy:
    """reference util/scheduling_strategies.py:44."""

    def __init__(
        self,
        placement_group: PlacementGroup,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = (
            placement_group_bundle_index
        )
        self.placement_group_capture_child_tasks = (
            placement_group_capture_child_tasks
        )


_GROUPS: Dict[str, PlacementGroup] = {}


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    """reference placement_group() :126. Reservation is attempted
    immediately; pg.ready() blocks until it succeeds."""
    pg = PlacementGroup(bundles, strategy, name)
    _GROUPS[pg.id] = pg
    from ray_tpu.core.api import _require_runtime

    pg._try_reserve(_require_runtime())
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    """reference remove_placement_group."""
    pg.remove()
