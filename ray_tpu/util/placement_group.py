"""Placement groups: gang resource reservation.

Counterpart of the reference's ``python/ray/util/placement_group.py:32``
(PlacementGroup, ``placement_group() :126``) and the raylet-side 2PC
bundle reservation (``raylet/placement_group_resource_manager.h``),
scoped to the single-host runtime: a group atomically reserves its
bundles' CPUs out of the scheduler pool; tasks/actors submitted with
``PlacementGroupSchedulingStrategy`` draw admission from the group's
reservation instead of the global pool. On a TPU pod the accelerator
side of gang placement is the jax mesh itself (devices are co-scheduled
by construction); this covers the CPU-fleet side."""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional


class PlacementGroup:
    """reference placement_group.py:32."""

    def __init__(self, bundles: List[Dict[str, float]], strategy: str,
                 name: str = ""):
        self.id = uuid.uuid4().hex[:16]
        self.bundles = [dict(b) for b in bundles]
        self.strategy = strategy
        self.name = name
        self._lock = threading.Lock()
        self._reserved = False
        self._removed = False
        self._ready_event = threading.Event()
        # per-bundle used CPUs (admission control inside the group)
        self._bundle_used = [0.0] * len(bundles)

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def total_cpus(self) -> float:
        return float(sum(b.get("CPU", 0.0) for b in self.bundles))

    # -- reservation against the runtime ----------------------------------

    def _try_reserve(self, rt) -> bool:
        with rt.lock:
            need = self.total_cpus()
            if need > rt.available_cpus + 1e-9:
                return False
            rt.available_cpus -= need
        with self._lock:
            self._reserved = True
        self._ready_event.set()
        # tasks queued against this group may now be admissible
        rt._dispatch_pending()
        return True

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the bundles are reserved (reference pg.ready()).
        Retries as capacity frees up."""
        from ray_tpu.core.api import _require_runtime

        rt = _require_runtime()
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while not self._ready_event.is_set():
            if self._removed:
                return False
            if self._try_reserve(rt):
                break
            if (
                deadline is not None
                and time.monotonic() >= deadline
            ):
                return False
            time.sleep(0.01)
        return True

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    # -- admission for member tasks (runtime lock held) -------------------

    def _fits(self, num_cpus: float, bundle_index: int = -1) -> bool:
        if not self._reserved or self._removed:
            return False
        with self._lock:
            if bundle_index >= 0:
                cap = self.bundles[bundle_index].get("CPU", 0.0)
                return (
                    self._bundle_used[bundle_index] + num_cpus
                    <= cap + 1e-9
                )
            for i, b in enumerate(self.bundles):
                if (
                    self._bundle_used[i] + num_cpus
                    <= b.get("CPU", 0.0) + 1e-9
                ):
                    return True
            return False

    def _acquire(self, num_cpus: float, bundle_index: int = -1) -> int:
        """→ the bundle index actually charged (the admission record
        releases exactly this bundle later)."""
        with self._lock:
            if bundle_index < 0:
                for i, b in enumerate(self.bundles):
                    if (
                        self._bundle_used[i] + num_cpus
                        <= b.get("CPU", 0.0) + 1e-9
                    ):
                        bundle_index = i
                        break
            self._bundle_used[bundle_index] += num_cpus
            return bundle_index

    def _release(self, num_cpus: float, bundle_index: int) -> None:
        with self._lock:
            if 0 <= bundle_index < len(self._bundle_used):
                self._bundle_used[bundle_index] = max(
                    0.0, self._bundle_used[bundle_index] - num_cpus
                )

    def remove(self) -> None:
        from ray_tpu.core.api import _require_runtime

        if self._removed:
            return
        self._removed = True
        if self._reserved:
            rt = _require_runtime()
            with rt.lock:
                rt.available_cpus += self.total_cpus()
            self._reserved = False
        _GROUPS.pop(self.id, None)

    def __repr__(self):
        return (
            f"PlacementGroup(id={self.id[:8]}, "
            f"bundles={self.bundles}, reserved={self._reserved})"
        )


class PlacementGroupSchedulingStrategy:
    """reference util/scheduling_strategies.py:44."""

    def __init__(
        self,
        placement_group: PlacementGroup,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = (
            placement_group_bundle_index
        )
        self.placement_group_capture_child_tasks = (
            placement_group_capture_child_tasks
        )


_GROUPS: Dict[str, PlacementGroup] = {}


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    """reference placement_group() :126. Reservation is attempted
    immediately; pg.ready() blocks until it succeeds."""
    pg = PlacementGroup(bundles, strategy, name)
    _GROUPS[pg.id] = pg
    from ray_tpu.core.api import _require_runtime

    pg._try_reserve(_require_runtime())
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    """reference remove_placement_group."""
    pg.remove()
