from ray_tpu.util.placement_group import (
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)

__all__ = [
    "placement_group",
    "remove_placement_group",
    "PlacementGroup",
    "PlacementGroupSchedulingStrategy",
]
