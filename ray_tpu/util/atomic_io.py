"""The ONE atomic-write implementation (durability contract, RTA009).

Eight modules used to hand-roll some prefix of the crash-safe write
chain — temp file → flush → ``os.fsync`` → ``os.replace`` →
directory fsync — and several skipped the fsyncs: a host crash could
publish a rename pointing at unwritten data blocks, or a directory
entry that never made it to disk, on the exact files the recovery
layer trusts (checkpoints, stream snapshots, experiment state, AOT
cache entries). This module centralizes the chain; the static
analyzer's RTA009 rule flags any ``os.replace`` outside it, so the
discipline can no longer regress one call site at a time.

``Algorithm._atomic_write`` / ``Algorithm._fsync_dir`` remain as
thin delegates for existing callers.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable

__all__ = ["atomic_write", "fsync_dir"]


# ray-tpu: atomic-writer
def atomic_write(
    path: str,
    write_fn: Callable,
    *,
    sync_dir: bool = True,
) -> None:
    """Write ``path`` through a same-directory temp file so a crash
    mid-save leaves either the old complete file or the new complete
    file — never a truncated one.

    fsync before the rename (the replace must not be reordered ahead
    of the data blocks), then — unless ``sync_dir=False`` — fsync the
    parent DIRECTORY: the rename itself lives in the directory inode,
    and without this a host crash can leave an entry pointing at the
    old (or no) file even though the data blocks hit disk. Pass
    ``sync_dir=False`` only when the caller batches several writes
    and issues one :func:`fsync_dir` at the end (the
    ``save_checkpoint`` shape).
    """
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".",
        prefix=os.path.basename(path) + ".tmp.",
    )
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if sync_dir:
        fsync_dir(os.path.dirname(path) or ".")


# ray-tpu: atomic-writer
def fsync_dir(path: str) -> None:
    """Flush a directory's entries (renames/unlinks) to disk. Best
    effort: platforms without directory fds are a no-op."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
