"""ActorPool: round-robin work distribution over a fixed set of
actors.

Counterpart of the reference's ``ray/util/actor_pool.py`` — the same
submit/get_next/get_next_unordered/map/map_unordered surface over a
list of actor handles, tracking which actor is free and preserving
submission order where asked. Interface-parity module: the public
surface (and therefore the natural free/busy + ordered-sequence
state machine behind it) deliberately matches the reference API;
the implementation is original, like ``models/preprocessors.py``
and ``env/wrappers.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu as ray


class ActorPool:
    def __init__(self, actors: List):
        self._idle = list(actors)
        self._inflight = {}
        self._ordered_refs = {}
        self._seq_submit = 0
        self._seq_return = 0
        self._backlog: List = []

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """``fn(actor, value) -> ObjectRef``; queues if all actors are
        busy (reference actor_pool.py submit)."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._inflight[ref] = (
                self._seq_submit,
                actor,
                fn,
            )
            self._ordered_refs[self._seq_submit] = ref
            self._seq_submit += 1
        else:
            self._backlog.append((fn, value))

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._backlog:
            fn, value = self._backlog.pop(0)
            self.submit(fn, value)

    def has_next(self) -> bool:
        return bool(self._inflight) or bool(
            self._backlog
        )

    def get_next(self, timeout: float = None):
        """Next result in SUBMISSION order. Invariant: whenever work
        is outstanding, the next-return index has a dispatched future
        (queued submits imply busy actors imply dispatched futures
        with lower indices) — same reasoning as the reference."""
        if not self.has_next():
            raise StopIteration("no more results")
        if self._seq_return not in self._ordered_refs:
            raise ValueError(
                "ordered get_next() cannot follow "
                "get_next_unordered() on the same pool"
            )
        ref = self._ordered_refs.pop(self._seq_return)
        self._seq_return += 1
        _, actor, _ = self._inflight.pop(ref)
        value = ray.get(ref, timeout=timeout)
        self._return_actor(actor)
        return value

    def get_next_unordered(self, timeout: float = None):
        """Whichever outstanding result lands first."""
        if not self._inflight:
            raise StopIteration("no pending results")
        ready, _ = ray.wait(
            list(self._inflight),
            num_returns=1,
            timeout=timeout,
        )
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        index, actor, _ = self._inflight.pop(ref)
        self._ordered_refs.pop(index, None)
        value = ray.get(ref, timeout=timeout)
        self._return_actor(actor)
        return value

    def map(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self._inflight or self._backlog:
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)
