"""ActorPool: round-robin work distribution over a fixed set of
actors.

Counterpart of the reference's ``ray/util/actor_pool.py`` — the same
submit/get_next/get_next_unordered/map/map_unordered surface over a
list of actor handles, tracking which actor is free and preserving
submission order where asked.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu as ray


class ActorPool:
    def __init__(self, actors: List):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List = []

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """``fn(actor, value) -> ObjectRef``; queues if all actors are
        busy (reference actor_pool.py submit)."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (
                self._next_task_index,
                actor,
                fn,
            )
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(
            self._pending_submits
        )

    def get_next(self, timeout: float = None):
        """Next result in SUBMISSION order. Invariant: whenever work
        is outstanding, the next-return index has a dispatched future
        (queued submits imply busy actors imply dispatched futures
        with lower indices) — same reasoning as the reference."""
        if not self.has_next():
            raise StopIteration("no more results")
        if self._next_return_index not in self._index_to_future:
            raise ValueError(
                "ordered get_next() cannot follow "
                "get_next_unordered() on the same pool"
            )
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        _, actor, _ = self._future_to_actor.pop(ref)
        value = ray.get(ref, timeout=timeout)
        self._return_actor(actor)
        return value

    def get_next_unordered(self, timeout: float = None):
        """Whichever outstanding result lands first."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray.wait(
            list(self._future_to_actor),
            num_returns=1,
            timeout=timeout,
        )
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        index, actor, _ = self._future_to_actor.pop(ref)
        self._index_to_future.pop(index, None)
        value = ray.get(ref, timeout=timeout)
        self._return_actor(actor)
        return value

    def map(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self._future_to_actor or self._pending_submits:
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)
