"""``multiprocessing.Pool``-compatible API over remote tasks.

Counterpart of the reference's ``ray/util/multiprocessing/pool.py`` —
drop-in ``Pool`` with map/starmap/apply/async variants and chunking,
so stdlib-Pool code ports without rewrites. Work runs as ray_tpu
tasks (the "processes" count only caps in-flight chunks; actual
parallelism is the runtime's CPU pool).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu as ray


@ray.remote
def _run_chunk(fn, chunk, star):
    if star:
        return [fn(*args) for args in chunk]
    return [fn(x) for x in chunk]


class AsyncResult:
    """reference pool.py AsyncResult: .get/.wait/.ready over the
    underlying chunk refs."""

    def __init__(self, refs: List, flatten: bool = True):
        self._refs = refs
        self._flatten = flatten

    def get(self, timeout: Optional[float] = None):
        outs = ray.get(self._refs, timeout=timeout)
        if not self._flatten:
            return outs[0][0]
        return [x for chunk in outs for x in chunk]

    def wait(self, timeout: Optional[float] = None) -> None:
        ray.wait(
            self._refs,
            num_returns=len(self._refs),
            timeout=timeout,
        )

    def ready(self) -> bool:
        ready, _ = ray.wait(
            self._refs, num_returns=len(self._refs), timeout=0
        )
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    def __init__(self, processes: Optional[int] = None):
        ray.init(ignore_reinit_error=True)
        self._processes = processes or 4
        self._closed = False

    # -- sync ------------------------------------------------------------

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List:
        return self.map_async(fn, iterable, chunksize).get()

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List:
        return self.starmap_async(fn, iterable, chunksize).get()

    def apply(self, fn: Callable, args=(), kwargs=None) -> Any:
        return self.apply_async(fn, args, kwargs).get()

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        for ref in self._submit(fn, iterable, chunksize, star=False):
            yield from ray.get(ref)

    # -- async -----------------------------------------------------------

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(
            self._submit(fn, iterable, chunksize, star=False)
        )

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(
            self._submit(fn, iterable, chunksize, star=True)
        )

    def apply_async(self, fn, args=(), kwargs=None) -> AsyncResult:
        kwargs = kwargs or {}
        ref = _run_chunk.remote(
            lambda *_a: fn(*args, **kwargs), [()], True
        )
        return AsyncResult([ref], flatten=False)

    def _submit(self, fn, iterable, chunksize, star) -> List:
        if self._closed:
            raise ValueError("Pool not running")
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [
            _run_chunk.remote(fn, items[i : i + chunksize], star)
            for i in range(0, len(items), chunksize)
        ]

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        pass  # tasks are awaited via their results

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
