"""Device rollout engine: act → step → postprocess as ONE mesh program.

The device half of the two rollout lanes (docs/pipeline.md). For a
:class:`~ray_tpu.env.jax_env.JaxVectorEnv`, the whole rollout —
policy forward + exploration sampling, vmapped env step, auto-reset,
GAE postprocess, advantage standardization — lowers into one
``sharded_jit`` program over the learner mesh, with the env-state tree
row-sharded like a batch (``sharding/specs.py``) and the policy's rng
threaded in the host-visible split order (one split per env step — the
exact stream the actor lane's local worker consumes), so a fixed seed
produces the actor lane's trajectories bit for bit
(tests/test_jax_env.py).

Two consumption modes:

- :meth:`JaxRolloutEngine.rollout` — one dispatch produces a
  device-resident trajectory batch (``(N·T, ...)`` columns, env-major
  row order like the host lane's concat). On-policy algorithms learn
  from it in place; off-policy algorithms insert the rows into a
  :class:`~ray_tpu.execution.replay_buffer.DeviceReplayBuffer` via
  ``add_device_tree`` — rollout rows never touch the host either way.
- :meth:`JaxRolloutEngine.superstep_feed` — the feed descriptor for
  ``JaxPolicy.learn_rollout_superstep``: K × [rollout + SGD-nest
  update] fuse into ONE dispatched program
  (``sharding/superstep.build_superstep_fn``'s rollout feed), zero
  batch bytes over H2D.

Auto-reset follows the terminal-observation contract of
``env/jax_env.py``: NEXT_OBS is the final (pre-reset) observation, the
successor row's OBS the reset observation; GAE bootstraps 0 across
``terminated`` and V(final obs) across ``truncated``
(``ops/gae.compute_gae_fragment``) — matching the host sampler +
``evaluation/postprocessing.py`` exactly.

Episode returns/lengths accumulate in the carry and drain with the
stats readback as ``(T, N)`` masked arrays — the lane's RolloutMetrics
come back without any per-step host work.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.env.jax_env import JaxVectorEnv, env_keys, tree_where
from ray_tpu.evaluation.metrics import RolloutMetrics
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing

# columns the PPO-family learn feed keeps (mirrors
# ``_batch_to_train_tree`` semantics: NEXT_OBS dropped when the loss
# never reads it — JaxPolicy._ship_next_obs)
_LEARN_DROP = (SampleBatch.NEXT_OBS, SampleBatch.AGENT_INDEX, SampleBatch.T)


class RolloutSuperstepFeed:
    """Descriptor handing the engine's per-shard rollout body + env
    carry to ``JaxPolicy.learn_rollout_superstep`` (the rollout feed
    of ``build_superstep_fn``)."""

    def __init__(self, carry, body, steps: int, key):
        self.carry = carry
        self.body = body
        self.steps = int(steps)
        self.key = key


def supports_jax_rollout_lane(policy, env) -> Tuple[bool, str]:
    """(ok, reason): whether (policy, env) can run on the device
    rollout lane. Callers fail fast at config time with ``reason``."""
    if not isinstance(env, JaxVectorEnv):
        return False, f"env {type(env).__name__} is not a JaxVectorEnv"
    if not getattr(policy, "supports_jax_rollout", False):
        return False, (
            f"policy {type(policy).__name__} cannot lower its act "
            "path (recurrent model, stateful exploration, or non-mesh "
            "backend)"
        )
    return True, ""


class JaxRolloutEngine:
    """One policy + one JaxVectorEnv, N env slots on the learner mesh.

    ``postprocess="gae"`` computes advantages/value targets in-program
    (on-policy); ``postprocess="none"`` emits raw transition rows
    (replay fill). ``seed`` follows the actor lane's worker
    convention (config seed; env ``i`` keyed ``seed + i``)."""

    def __init__(
        self,
        policy,
        env: JaxVectorEnv,
        num_envs: int,
        rollout_length: int,
        *,
        seed: Optional[int] = None,
        postprocess: str = "gae",
        standardize_advantages: bool = True,
    ):
        import jax

        from ray_tpu import sharding as sharding_lib

        ok, reason = supports_jax_rollout_lane(policy, env)
        if not ok:
            raise ValueError(f"jax rollout lane unavailable: {reason}")
        self.policy = policy
        self.env = env
        self.N = int(num_envs)
        self.T = int(rollout_length)
        self.mesh = policy.mesh
        self.n_shards = sharding_lib.num_shards(self.mesh)
        if self.N % self.n_shards:
            raise ValueError(
                f"num_envs {self.N} must divide the {self.n_shards} "
                "data shards (row-sharded env states)"
            )
        if postprocess not in ("gae", "none"):
            raise ValueError(f"unknown postprocess {postprocess!r}")
        self.postprocess = postprocess
        self.standardize = bool(standardize_advantages)
        self.gamma = float(policy.config.get("gamma", 0.99))
        self.lambda_ = float(policy.config.get("lambda", 1.0))
        self._seed = seed
        self._metrics: List[RolloutMetrics] = []
        self._rollout_fn = None
        self._body = None
        self.batch_size = self.N * self.T

        # initial env carry, resident and row-sharded from step zero
        keys = env_keys(seed, self.N)
        state = jax.jit(jax.vmap(env.init))(keys)
        state, obs = jax.jit(jax.vmap(env.reset))(state)
        carry = {
            "env": state,
            "obs": obs,
            "ep_ret": jax.numpy.zeros(self.N, jax.numpy.float32),
            "ep_len": jax.numpy.zeros(self.N, jax.numpy.int32),
        }
        self._carry = jax.device_put(
            carry, sharding_lib.batch_sharded(self.mesh)
        )

    # -- the per-shard rollout body --------------------------------------

    def _rollout_body(self):
        """``fn(params, carry, ro_rngs (T, 2), coeffs) -> (carry,
        batch, metrics)`` over THIS SHARD's env rows; runs inside
        ``shard_map`` (superstep scan slot or the standalone rollout
        program — same body, same numerics)."""
        if self._body is not None:
            return self._body
        import jax
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib
        from ray_tpu.ops.gae import compute_gae_fragment

        policy = self.policy
        env = self.env
        axis = sharding_lib.data_axis(self.mesh)
        n_loc = self.N // self.n_shards
        T = self.T
        step_b = jax.vmap(env.step)
        reset_b = jax.vmap(env.reset)
        gamma, lam = self.gamma, self.lambda_
        mode = self.postprocess
        standardize = self.standardize and mode == "gae"
        value_fwd = policy.model_forward

        def body(params, carry, ro_rngs, coeffs):
            def step(c, key_t):
                env_state, obs, ep_ret, ep_len = c
                # pin each sub-program's fusion boundary so it
                # compiles like the actor lane's standalone jitted
                # programs (action fn / vmapped env step / reset) —
                # the lane parity contract (docs/data_plane.md)
                params_b, obs_b, key_t = jax.lax.optimization_barrier(
                    (params, obs, key_t)
                )
                actions, _, extra, _ = policy._action_step_body(
                    params_b, obs_b, key_t, coeffs,
                    explore=True, expl_state=(),
                )
                # pin the OUTPUTS as well: the value head's result
                # feeds the in-program GAE below, and without a
                # barrier XLA fuses it differently than the actor
                # lane's standalone action program (last-ulp drift)
                actions, extra = jax.lax.optimization_barrier(
                    (actions, extra)
                )
                env_state_b, actions_b = jax.lax.optimization_barrier(
                    (env_state, actions)
                )
                env_state2, obs2, rew, term, trunc = step_b(
                    env_state_b, actions_b
                )
                done = term | trunc
                env_state2b = jax.lax.optimization_barrier(env_state2)
                env_state3, obs3 = reset_b(env_state2b)
                rew = rew.astype(jnp.float32)
                ep_ret2 = ep_ret + rew
                ep_len2 = ep_len + 1
                row = {
                    SampleBatch.OBS: obs,
                    SampleBatch.NEXT_OBS: obs2,
                    SampleBatch.ACTIONS: actions,
                    SampleBatch.REWARDS: rew,
                    SampleBatch.TERMINATEDS: term,
                    SampleBatch.TRUNCATEDS: trunc,
                    SampleBatch.T: ep_len,
                    **extra,
                }
                if mode == "gae":
                    # fresh V(final obs) for boundary/tail bootstraps
                    # — same (N,) forward shape as the act-path value,
                    # so the two lanes' bootstraps agree
                    obs2_b = jax.lax.optimization_barrier(obs2)
                    _, v_next, _ = value_fwd(params_b, obs2_b)
                    row["_v_next"] = v_next
                metrics = {
                    "ep_return": jnp.where(done, ep_ret2, 0.0),
                    "ep_length": jnp.where(done, ep_len2, 0),
                    "done": done,
                }
                env_state = tree_where(done, env_state3, env_state2)
                obs_next = tree_where(done, obs3, obs2)
                ep_ret = jnp.where(done, 0.0, ep_ret2)
                ep_len = jnp.where(done, 0, ep_len2)
                return (
                    (env_state, obs_next, ep_ret, ep_len),
                    (row, metrics),
                )

            c0 = (
                carry["env"],
                carry["obs"],
                carry["ep_ret"],
                carry["ep_len"],
            )
            (env_state, obs, ep_ret, ep_len), (rows, metrics) = (
                jax.lax.scan(step, c0, ro_rngs)
            )
            carry = {
                "env": env_state,
                "obs": obs,
                "ep_ret": ep_ret,
                "ep_len": ep_len,
            }
            # global env index of each local row (host-lane
            # AGENT_INDEX semantics)
            shard0 = jax.lax.axis_index(axis) * n_loc
            rows[SampleBatch.AGENT_INDEX] = jnp.broadcast_to(
                shard0 + jnp.arange(n_loc, dtype=jnp.int32), (T, n_loc)
            )
            if mode == "gae":
                values = rows[SampleBatch.VF_PREDS]  # (T, N)
                fresh = rows.pop("_v_next")  # (T, N)
                term = rows[SampleBatch.TERMINATEDS]
                done = term | rows[SampleBatch.TRUNCATEDS]
                # interior rows reuse the act-path values exactly like
                # the host lane's vpred_t[1:]; boundary/tail rows use
                # the fresh terminal-observation values
                shifted = jnp.concatenate(
                    [values[1:], fresh[-1:]], axis=0
                )
                next_values = jnp.where(done, fresh, shifted)
                adv, vt = compute_gae_fragment(
                    rows[SampleBatch.REWARDS].T,
                    values.T,
                    next_values.T,
                    term.T,
                    done.T,
                    gamma,
                    lam,
                )  # (N, T)
                if standardize:
                    m = jax.lax.pmean(adv.mean(), axis)
                    var = jax.lax.pmean(((adv - m) ** 2).mean(), axis)
                    adv = (adv - m) / jnp.maximum(
                        1e-4, jnp.sqrt(var)
                    )
                rows[SampleBatch.ADVANTAGES] = adv.T
                rows[SampleBatch.VALUE_TARGETS] = vt.T

            # (T, N, ...) -> env-major (N*T, ...) rows, the host
            # lane's concat order
            def to_rows(v):
                v = jnp.swapaxes(v, 0, 1)
                return v.reshape((n_loc * T,) + v.shape[2:])

            batch = {k: to_rows(v) for k, v in rows.items()}
            return carry, batch, metrics

        self._body = body
        return body

    # -- fused rollout+learn feed ----------------------------------------

    def superstep_feed(self) -> RolloutSuperstepFeed:
        self._pre_dispatch()
        return RolloutSuperstepFeed(
            carry=self._carry,
            body=self._learn_feed_body(),
            steps=self.T,
            key=(
                "jax_rollout",
                type(self.env).__name__,
                self.N,
                self.T,
                self.postprocess,
                self.standardize,
            ),
        )

    def _learn_feed_body(self):
        """The superstep-slot body: rollout, then hand the UPDATE the
        learn-column subset (NEXT_OBS etc. stay out of the nest's
        minibatch gathers, mirroring ``_batch_to_train_tree``)."""
        body = self._rollout_body()

        def fn(params, carry, ro_rngs, coeffs):
            carry, batch, metrics = body(params, carry, ro_rngs, coeffs)
            learn = {
                k: v for k, v in batch.items() if k not in _LEARN_DROP
            }
            return carry, learn, metrics

        return fn

    def advance(self, carry, metrics) -> None:
        """Commit the carry a fused superstep returned and absorb its
        drained (host numpy) metrics tree."""
        self._carry = carry
        self._record_metrics(metrics)
        telemetry_metrics.inc_env_steps_on_device(
            int(np.asarray(metrics["done"]).size)
        )

    # -- standalone rollout (replay fill / per-update lane) --------------

    def rollout(self):
        """One dispatched rollout: returns ``(device batch tree,
        batch_size)`` with the env carry advanced and episode metrics
        absorbed. The policy's rng is split T times host-side (the
        actor lane's per-step order)."""
        import jax
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib

        policy = self.policy
        if self._rollout_fn is None:
            from jax.sharding import PartitionSpec as P

            axis = sharding_lib.data_axis(self.mesh)
            body = self._rollout_body()

            def program(params, carry, ro_rngs, coeffs):
                return body(params, carry, ro_rngs, coeffs)

            # params enter per their spec tree (P() = replicated on
            # un-partitioned policies; per-leaf model-axis slices for
            # partitioned ones — the model inserts its own collectives)
            p_ps = getattr(policy, "param_pspecs", None)
            p_ps = P() if p_ps is None else p_ps
            sharded = jax.shard_map(
                program,
                mesh=self.mesh,
                in_specs=(p_ps, P(axis), P(), P()),
                out_specs=(
                    P(axis),
                    P(axis),
                    P(None, axis),
                ),
            )
            rep = sharding_lib.replicated(self.mesh)
            p_sh = getattr(policy, "param_shardings", None) or rep
            dat = sharding_lib.batch_sharded(self.mesh)
            met = sharding_lib.batch_sharded(self.mesh, ndim_prefix=2)
            self._rollout_fn = sharding_lib.sharded_jit(
                sharded,
                in_specs=(p_sh, dat, rep, rep),
                out_specs=(dat, dat, met),
                label=(
                    f"jax_rollout[{type(self.env).__name__}:"
                    f"{self.N}x{self.T}]"
                ),
            )
        coeffs = self._pre_dispatch()
        keys = []
        for _ in range(self.T):
            policy._rng, r = jax.random.split(policy._rng)
            keys.append(r)
        ro_rngs = jnp.stack(keys)
        telemetry_metrics.add_h2d_bytes("rollout", int(ro_rngs.nbytes))
        with tracing.start_span(
            "rollout:device", num_envs=self.N, steps=self.T
        ):
            self._carry, batch, metrics = self._rollout_fn(
                policy.params, self._carry, ro_rngs, coeffs
            )
            metrics = jax.device_get(metrics)
        self._record_metrics(metrics)
        telemetry_metrics.inc_env_steps_on_device(self.batch_size)
        return dict(batch), self.batch_size

    def learn_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        """The learn-column subset of a :meth:`rollout` batch (what
        the fused feed hands the nest)."""
        return {k: v for k, v in batch.items() if k not in _LEARN_DROP}

    def _pre_dispatch(self):
        """Host-side per-dispatch upkeep mirroring compute_actions:
        advance exploration schedules, then snapshot coeffs."""
        policy = self.policy
        policy.exploration.update_coeffs(
            policy.coeff_values, policy.global_timestep
        )
        return policy._coeff_array()

    # -- episode metrics --------------------------------------------------

    def _record_metrics(self, metrics) -> None:
        done = np.asarray(metrics["done"]).reshape(-1)
        if not done.any():
            return
        rets = np.asarray(metrics["ep_return"]).reshape(-1)[done]
        lens = np.asarray(metrics["ep_length"]).reshape(-1)[done]
        for r, l in zip(rets, lens):
            self._metrics.append(RolloutMetrics(int(l), float(r)))

    def get_metrics(self) -> List[RolloutMetrics]:
        out = self._metrics
        self._metrics = []
        return out
