"""Replay buffers (uniform + prioritized), host-RAM resident.

Counterpart of the reference's
``rllib/utils/replay_buffers/{replay_buffer,prioritized_replay_buffer}.py``
(PrioritizedReplayBuffer ``:19``) and the segment trees
(``rllib/execution/segment_tree.py``). TPU-first: storage is columnar
(pre-allocated numpy ring arrays per column) instead of a deque of
per-timestep dicts, so sampling a training batch is a single fancy-index
gather producing learner-ready arrays with zero python-loop work.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.ops.segment_tree import MinSegmentTree, SumSegmentTree


class ReplayBuffer:
    """Uniform ring buffer (reference replay_buffer.py ReplayBuffer)."""

    def __init__(self, capacity: int = 10000, seed: Optional[int] = None):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self._num_added = 0

    def __len__(self) -> int:
        return self._size

    @property
    def num_added(self) -> int:
        return self._num_added

    def _ensure_cols(self, batch: SampleBatch):
        for k, v in batch.items():
            if not isinstance(v, np.ndarray) or v.dtype == object:
                continue
            if k not in self._cols:
                self._cols[k] = np.zeros(
                    (self.capacity,) + v.shape[1:], v.dtype
                )

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if n == 0:
            return
        self._ensure_cols(batch)
        idx = (self._idx + np.arange(n)) % self.capacity
        for k, col in self._cols.items():
            if k in batch:
                col[idx] = batch[k]
        self._idx = int((self._idx + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._num_added += n

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, num_items)
        return self._make_batch(idx)

    def _make_batch(self, idx: np.ndarray) -> SampleBatch:
        return SampleBatch(
            {k: col[idx] for k, col in self._cols.items()}
        )

    def stats(self) -> Dict:
        return {"size": self._size, "num_added": self._num_added}

    def get_state(self) -> Dict:
        return {
            "cols": {k: v[: self._size].copy() for k, v in self._cols.items()},
            "idx": self._idx,
            "size": self._size,
            "num_added": self._num_added,
        }

    def set_state(self, state: Dict) -> None:
        self._size = state["size"]
        self._idx = state["idx"]
        self._num_added = state["num_added"]
        for k, v in state["cols"].items():
            self._cols[k] = np.zeros(
                (self.capacity,) + v.shape[1:], v.dtype
            )
            self._cols[k][: self._size] = v


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference
    prioritized_replay_buffer.py:19), vectorized over the whole sample
    batch via the numpy segment trees."""

    def __init__(
        self,
        capacity: int = 10000,
        alpha: float = 0.6,
        seed: Optional[int] = None,
    ):
        super().__init__(capacity, seed)
        assert alpha >= 0
        self._alpha = alpha
        cap2 = 1
        while cap2 < capacity:
            cap2 *= 2
        self._sum_tree = SumSegmentTree(cap2)
        self._min_tree = MinSegmentTree(cap2)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch) -> None:
        # new samples enter at max priority so they are trained on at
        # least once (one insertion code path: add_with_priorities)
        self.add_with_priorities(
            batch, np.full(batch.count, self._max_priority)
        )

    def add_with_priorities(
        self, batch: SampleBatch, priorities: np.ndarray
    ) -> None:
        """Insert with caller-supplied initial priorities (Ape-X:
        workers/driver compute initial TD errors; reference
        apex ReplayActor.add_batch)."""
        n = batch.count
        if n == 0:
            return
        idx = (self._idx + np.arange(n)) % self.capacity
        ReplayBuffer.add(self, batch)
        self.update_priorities(idx, np.asarray(priorities, np.float64))

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        total = self._sum_tree.sum(0, self._size)
        mass = (
            self._rng.random(num_items) + np.arange(num_items)
        ) / num_items * total
        idx = self._sum_tree.find_prefixsum_idx(mass)
        idx = np.clip(idx, 0, self._size - 1)

        p_min = self._min_tree.min(0, self._size) / total
        max_weight = (p_min * self._size) ** (-beta)
        p_sample = self._sum_tree[idx] / total
        weights = (p_sample * self._size) ** (-beta) / max_weight

        batch = self._make_batch(idx)
        batch["weights"] = weights.astype(np.float32)
        batch["batch_indexes"] = idx.astype(np.int64)
        return batch

    def update_priorities(
        self, idx: np.ndarray, priorities: np.ndarray
    ) -> None:
        priorities = np.maximum(np.asarray(priorities, np.float64), 1e-6)
        self._sum_tree.set_items(idx, priorities**self._alpha)
        self._min_tree.set_items(idx, priorities**self._alpha)
        self._max_priority = max(
            self._max_priority, float(priorities.max())
        )


class MultiAgentReplayBuffer:
    """Per-policy buffers (reference multi_agent_replay_buffer.py)."""

    def __init__(
        self,
        capacity: int = 10000,
        prioritized: bool = False,
        alpha: float = 0.6,
        seed: Optional[int] = None,
    ):
        self.capacity = capacity
        self.prioritized = prioritized
        self.alpha = alpha
        self.seed = seed
        self.buffers: Dict[str, ReplayBuffer] = {}

    def _buffer(self, pid: str) -> ReplayBuffer:
        if pid not in self.buffers:
            if self.prioritized:
                self.buffers[pid] = PrioritizedReplayBuffer(
                    self.capacity, self.alpha, self.seed
                )
            else:
                self.buffers[pid] = ReplayBuffer(self.capacity, self.seed)
        return self.buffers[pid]

    def add(self, batch) -> None:
        from ray_tpu.data.sample_batch import (
            DEFAULT_POLICY_ID,
            MultiAgentBatch,
        )

        if isinstance(batch, SampleBatch):
            batch = batch.as_multi_agent()
        for pid, sb in batch.policy_batches.items():
            self._buffer(pid).add(sb)

    def sample(self, num_items: int, **kwargs):
        from ray_tpu.data.sample_batch import MultiAgentBatch

        out = {}
        for pid, buf in self.buffers.items():
            if len(buf) >= num_items:
                out[pid] = (
                    buf.sample(num_items, **kwargs)
                    if isinstance(buf, PrioritizedReplayBuffer)
                    else buf.sample(num_items)
                )
        return MultiAgentBatch(out, num_items)

    def __len__(self) -> int:
        return max((len(b) for b in self.buffers.values()), default=0)
