"""Replay buffers: host-RAM ring + the device-resident data plane.

Counterpart of the reference's
``rllib/utils/replay_buffers/{replay_buffer,prioritized_replay_buffer}.py``
(PrioritizedReplayBuffer ``:19``) and the segment trees
(``rllib/execution/segment_tree.py``). TPU-first: storage is columnar
(pre-allocated ring arrays per column) instead of a deque of
per-timestep dicts, so sampling a training batch is a single
fancy-index gather producing learner-ready arrays with zero
python-loop work.

Two storage planes (docs/data_plane.md):

- :class:`ReplayBuffer` / :class:`PrioritizedReplayBuffer` — numpy
  rings on the host. Every learn step re-transfers its sampled rows
  host→device; at SAC-style replay ratios each frame crosses the wire
  dozens of times.
- :class:`DeviceReplayBuffer` / :class:`DevicePrioritizedReplayBuffer`
  — column rings living as device arrays on the learner mesh
  (``ray_tpu.sharding``): inserts are one donated jit'd scatter (each
  transition crosses H2D exactly once), samples are one jit'd gather
  whose output feeds ``JaxPolicy.learn_on_device_batch`` directly.
  The index draw stays HOST-seeded (same generator, same call order
  as the host ring), so a fixed seed produces bit-identical learn
  results on either plane. Priorities stay host-side (the numpy sum
  tree — a device sum tree is an open ROADMAP item); only rows live
  on device. A capacity/memory projection at first insert spills to
  the host ring when the buffer wouldn't fit.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.ops.segment_tree import MinSegmentTree, SumSegmentTree


class ReplayBuffer:
    """Uniform ring buffer (reference replay_buffer.py ReplayBuffer)."""

    def __init__(self, capacity: int = 10000, seed: Optional[int] = None):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self._num_added = 0

    def __len__(self) -> int:
        return self._size

    @property
    def num_added(self) -> int:
        return self._num_added

    def _ensure_cols(self, batch: SampleBatch):
        for k, v in batch.items():
            if not isinstance(v, np.ndarray) or v.dtype == object:
                continue
            if k not in self._cols:
                self._cols[k] = np.zeros(
                    (self.capacity,) + v.shape[1:], v.dtype
                )

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if n == 0:
            return
        self._ensure_cols(batch)
        idx = (self._idx + np.arange(n)) % self.capacity
        for k, col in self._cols.items():
            if k in batch:
                col[idx] = batch[k]
        self._idx = int((self._idx + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._num_added += n

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, num_items)
        return self._make_batch(idx)

    def draw_index_sets(self, k: int, num_items: int) -> np.ndarray:
        """``k`` uniform draws of ``num_items`` rows as a ``(k, n)``
        index matrix — the superstep's pre-drawn batch schedule. The
        draws are k SEQUENTIAL generator calls (never one k·n call):
        the generator consumes its stream in the host ring's exact
        per-update call order, so a fixed seed stays bit-identical to
        k individual ``sample`` calls."""
        return np.stack(
            [
                self._rng.integers(0, self._size, num_items)
                for _ in range(k)
            ]
        )

    def _make_batch(self, idx: np.ndarray) -> SampleBatch:
        return SampleBatch(
            {k: col[idx] for k, col in self._cols.items()}
        )

    def stats(self) -> Dict:
        return {"size": self._size, "num_added": self._num_added}

    def get_state(self) -> Dict:
        return {
            "cols": {k: v[: self._size].copy() for k, v in self._cols.items()},
            "idx": self._idx,
            "size": self._size,
            "num_added": self._num_added,
        }

    def set_state(self, state: Dict) -> None:
        self._size = state["size"]
        self._idx = state["idx"]
        self._num_added = state["num_added"]
        for k, v in state["cols"].items():
            self._cols[k] = np.zeros(
                (self.capacity,) + v.shape[1:], v.dtype
            )
            self._cols[k][: self._size] = v


def powered_priorities(priorities, alpha: float):
    """THE canonical priority→leaf transform: clamp to 1e-6, then the
    alpha-power — in host numpy f64, for BOTH tree planes. The power
    is the one op in the prioritized path that numpy and XLA round
    differently (last-ulp), so it stays host-side and the device tree
    receives already-powered leaves; everything downstream (sums,
    prefix descent, min, gathers) is exact f64 arithmetic on either
    plane. Returns ``(powered, clamped)`` — the clamped values feed
    the max-priority watermark exactly as the host tree's update
    does."""
    clamped = np.maximum(np.asarray(priorities, np.float64), 1e-6)
    return clamped**alpha, clamped


class _PrioritySampling:
    """Host-side proportional-priority machinery shared by the host
    and device prioritized buffers: numpy sum/min segment trees, the
    stratified index draw, IS-weight computation, and priority
    updates. One implementation on purpose — the device buffer keeps
    bit-identical sampling to the host ring because it runs exactly
    this code; only WHERE the rows live differs. (The device SUM TREE
    — ``replay_device_tree`` — overrides the tree walks with the
    bit-exact device programs of ``ops/segment_tree.DeviceSumTree``;
    this class remains the oracle both planes are asserted against.)"""

    def _init_priority_trees(self, capacity: int, alpha: float) -> None:
        assert alpha >= 0
        self._alpha = alpha
        cap2 = 1
        while cap2 < capacity:
            cap2 *= 2
        self._tree_capacity = cap2
        self._sum_tree = SumSegmentTree(cap2)
        self._min_tree = MinSegmentTree(cap2)
        self._max_priority = 1.0
        self._tree_op = "update"  # insert paths flip this transiently

    def _draw_prioritized(self, num_items: int, beta: float):
        """→ (row indices, IS weights float32) for one stratified
        proportional draw over the current ``self._size`` rows."""
        from ray_tpu.telemetry import metrics as telemetry_metrics

        total = self._sum_tree.sum(0, self._size)
        mass = (
            self._rng.random(num_items) + np.arange(num_items)
        ) / num_items * total
        idx = self._sum_tree.find_prefixsum_idx(mass)
        idx = np.clip(idx, 0, self._size - 1)

        p_min = self._min_tree.min(0, self._size) / total
        max_weight = (p_min * self._size) ** (-beta)
        p_sample = self._sum_tree[idx] / total
        weights = (p_sample * self._size) ** (-beta) / max_weight
        telemetry_metrics.inc_tree_op("sample", "host")
        return idx, weights.astype(np.float32)

    def draw_prioritized_sets(self, k: int, num_items: int, beta: float):
        """``k`` sequential stratified draws → ``(k, n)`` indices and
        IS weights. Priorities are NOT refreshed between the draws —
        the superstep's documented within-chain staleness
        (docs/data_plane.md); the generator call order matches k
        individual ``sample`` calls exactly."""
        idx, weights = zip(
            *(self._draw_prioritized(num_items, beta) for _ in range(k))
        )
        return np.stack(idx), np.stack(weights)

    def update_priorities(
        self, idx: np.ndarray, priorities: np.ndarray
    ) -> None:
        from ray_tpu.telemetry import metrics as telemetry_metrics

        powered, clamped = powered_priorities(priorities, self._alpha)
        self._sum_tree.set_items(idx, powered)
        self._min_tree.set_items(idx, powered)
        self._max_priority = max(
            self._max_priority, float(clamped.max())
        )
        telemetry_metrics.inc_tree_op(self._tree_op, "host")

    def _priority_state(self) -> Dict:
        """Raw (already alpha-powered) leaf values of the stored range
        + max priority — enough to rebuild both trees exactly."""
        idx = np.arange(self._size)
        return {
            "leaf_values": np.asarray(self._sum_tree[idx], np.float64)
            if self._size
            else np.zeros(0, np.float64),
            "max_priority": self._max_priority,
        }

    def _set_priority_state(self, state: Dict) -> None:
        vals = np.asarray(state["leaf_values"], np.float64)
        if len(vals):
            idx = np.arange(len(vals))
            self._sum_tree.set_items(idx, vals)
            self._min_tree.set_items(idx, vals)
        self._max_priority = float(state.get("max_priority", 1.0))


class PrioritizedReplayBuffer(_PrioritySampling, ReplayBuffer):
    """Proportional prioritized replay (reference
    prioritized_replay_buffer.py:19), vectorized over the whole sample
    batch via the numpy segment trees."""

    tree_plane = "host"

    def __init__(
        self,
        capacity: int = 10000,
        alpha: float = 0.6,
        seed: Optional[int] = None,
    ):
        super().__init__(capacity, seed)
        self._init_priority_trees(capacity, alpha)

    def add(self, batch: SampleBatch) -> None:
        # new samples enter at max priority so they are trained on at
        # least once (one insertion code path: add_with_priorities)
        self.add_with_priorities(
            batch, np.full(batch.count, self._max_priority)
        )

    def add_with_priorities(
        self, batch: SampleBatch, priorities: np.ndarray
    ) -> None:
        """Insert with caller-supplied initial priorities (Ape-X:
        workers/driver compute initial TD errors; reference
        apex ReplayActor.add_batch)."""
        n = batch.count
        if n == 0:
            return
        idx = (self._idx + np.arange(n)) % self.capacity
        ReplayBuffer.add(self, batch)
        self._tree_op = "insert"
        try:
            self.update_priorities(
                idx, np.asarray(priorities, np.float64)
            )
        finally:
            self._tree_op = "update"

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        from ray_tpu.util import tracing

        with tracing.start_span(
            "replay:sample", n=num_items, tree="host"
        ):
            idx, weights = self._draw_prioritized(num_items, beta)
            batch = self._make_batch(idx)
            batch["weights"] = weights
            batch["batch_indexes"] = idx.astype(np.int64)
            return batch

    def get_state(self) -> Dict:
        state = super().get_state()
        state["priorities"] = self._priority_state()
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "priorities" in state:
            self._set_priority_state(state["priorities"])


def resolve_device_resident(config: Dict, mesh=None) -> bool:
    """Resolve the ``replay_device_resident`` knob
    (docs/data_plane.md). ``True`` forces device placement (the
    memory projection at first insert can still spill). ``"auto"``
    (the default) turns it on exactly where it pays: a real
    accelerator behind a transfer boundary. On the CPU client
    "device" arrays live in the same host RAM — there is no wire to
    diet, and the extra insert/sample programs are pure overhead —
    so auto resolves off there. Auto also resolves off when
    ``train_batch_size`` doesn't divide the data shards (the host
    path's prepare_batch trims ragged batches; the device path keeps
    static shapes end to end)."""
    mode = config.get("replay_device_resident", "auto")
    if not mode:
        return False
    if mode == "auto":
        try:
            import jax

            devices = mesh.devices.flatten() if mesh is not None else (
                jax.devices()
            )
            if all(d.platform == "cpu" for d in devices):
                return False
        except Exception:
            return False
        shards = 1
        if mesh is not None:
            try:
                from ray_tpu.sharding import num_shards

                shards = num_shards(mesh)
            except Exception:
                shards = 1
        if int(config.get("train_batch_size", 0)) % max(1, shards):
            return False
    return True


def resolve_device_tree(config: Dict, mesh=None) -> bool:
    """Resolve the ``replay_device_tree`` knob (docs/data_plane.md
    "device sum tree"). Requires device-resident rows (the tree's
    whole point is an in-program draw→gather over resident rings).
    ``"auto"`` (default) engages only behind a real accelerator —
    on the CPU client the numpy tree walk shares the host RAM the
    "device" tree would live in, and the extra programs are pure
    overhead; ``True`` forces it anywhere (tests, benches)."""
    mode = config.get("replay_device_tree", "auto")
    if not mode:
        return False
    if not resolve_device_resident(config, mesh):
        return False
    if mode == "auto":
        try:
            import jax

            devices = mesh.devices.flatten() if mesh is not None else (
                jax.devices()
            )
            if all(d.platform == "cpu" for d in devices):
                return False
        except Exception:
            return False
    return True


class DeviceTrainBatch:
    """A sampled batch whose columns are device arrays, ready for
    ``JaxPolicy.learn_on_device_batch`` — the device plane's stand-in
    for a host :class:`SampleBatch` in the off-policy training loops.
    ``indices`` (host numpy) are the drawn ring positions, kept for
    prioritized-priority refresh without a device round trip."""

    is_device_resident = True

    def __init__(
        self,
        tree: Dict[str, Any],
        count: int,
        indices: Optional[np.ndarray] = None,
    ):
        self.tree = tree
        self.count = int(count)
        self.indices = indices

    def __len__(self) -> int:
        return self.count

    def env_steps(self) -> int:
        return self.count

    def __contains__(self, key) -> bool:
        return key in self.tree

    def __getitem__(self, key):
        return self.tree[key]

    def get(self, key, default=None):
        return self.tree.get(key, default)


class SuperstepRingFeed:
    """Feed descriptor handing the device replay rings to a policy's
    fused superstep program (``JaxPolicy.learn_superstep``): the scan
    gathers each update's rows from ``store`` in place using the
    host-pre-drawn ``(k, B)`` index matrix — replay rows never leave
    the mesh, and only ``idx`` (plus any ``extra`` stacked host
    columns, e.g. PER importance weights) cross host→device."""

    def __init__(self, store, idx, extra, gather_fn, shardings, key):
        self.store = store
        self.idx = idx
        self.extra = extra
        self.gather_fn = gather_fn
        self.shardings = shardings
        self.key = key  # compile-cache key: the stored column set


class DeviceReplayBuffer:
    """Uniform ring buffer whose column storage lives on the learner
    mesh (docs/data_plane.md).

    - **Insert** is one donated jit'd circular scatter per fragment:
      the host rows cross H2D exactly once, here, and never again.
      uint8 columns (pixel obs) are stored packed as uint32 lanes —
      the same element-width trick as ``_build_learn_fn``'s minibatch
      gather (MFU.md) — so the sample gather moves 4× wider elements.
    - **Sample** draws indices on the HOST from the same seeded
      generator (same call order) as the host :class:`ReplayBuffer`,
      then gathers rows in one jit'd program; a fixed seed therefore
      yields bit-identical learn results on either plane.
    - **Spill**: the first insert projects total storage bytes
      (``capacity ×`` row bytes); past ``memory_cap_bytes`` (default:
      60% of the device's reported ``bytes_limit``, unlimited when the
      backend reports none — e.g. the CPU client) everything delegates
      to a host ring built with the SAME generator object, so the
      spill changes placement, never sampling.
    """

    is_device_resident = True

    def __init__(
        self,
        capacity: int = 10000,
        seed: Optional[int] = None,
        mesh=None,
        memory_cap_bytes: Optional[int] = None,
        label: str = "default_policy",
        use_pallas=None,
        pallas_interpret: bool = False,
    ):
        from ray_tpu import sharding as sharding_lib

        self.capacity = int(capacity)
        self._rng = np.random.default_rng(seed)
        self.mesh = mesh if mesh is not None else sharding_lib.get_mesh()
        self.memory_cap_bytes = memory_cap_bytes
        self.label = label
        # None = auto: insert/sample row movement through the Pallas
        # row-copy kernels (ops/framestack.py) where they lower —
        # bitwise-identical data movement either way. Auto stays off on
        # multi-device meshes (the kernels address the local ring, not
        # a sharded one); a forced True is honored as-is (tests).
        self.use_pallas = use_pallas
        self.pallas_interpret = bool(pallas_interpret)
        self._store: Dict[str, Any] = {}  # name -> device ring array
        # name -> (row_shape, dtype, packed_as_uint32)
        self._meta: Dict[str, tuple] = {}
        self._idx = 0
        self._size = 0
        self._num_added = 0
        self._insert_fn = None
        self._sample_fn = None
        self._host: Optional[ReplayBuffer] = None  # spill fallback
        self.storage_bytes = 0

    # -- spill ----------------------------------------------------------

    @property
    def spilled(self) -> bool:
        return self._host is not None

    def _make_host_fallback(self) -> ReplayBuffer:
        buf = ReplayBuffer(self.capacity)
        # same generator OBJECT: the spill changes row placement, not
        # the index stream — fixed-seed runs stay bit-identical
        buf._rng = self._rng
        return buf

    def _resolve_memory_cap(self) -> Optional[int]:
        if self.memory_cap_bytes is not None:
            return int(self.memory_cap_bytes)
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit")
            if limit:
                return int(0.6 * float(limit))
        except Exception:
            pass
        return None  # backend reports no budget: no projection check

    # -- storage --------------------------------------------------------

    @staticmethod
    def _canonical(v: np.ndarray) -> np.ndarray:
        """Match jax's dtype canonicalization BEFORE the transfer:
        with x64 disabled a ``device_put`` of f64/i64 lands as
        f32/i32 anyway (that's what the host ring's learn path
        ships), so cast host-side — same values, half the wire
        bytes."""
        import jax

        if not jax.config.jax_enable_x64:
            if v.dtype == np.float64:
                return v.astype(np.float32)
            if v.dtype == np.int64:
                return v.astype(np.int32)
            if v.dtype == np.uint64:
                return v.astype(np.uint32)
        return v

    @staticmethod
    def _packable(shape: tuple, dtype) -> bool:
        inner = int(np.prod(shape)) if shape else 1
        return (
            np.dtype(dtype) == np.uint8
            and len(shape) >= 1
            and inner % 4 == 0
        )

    def _ensure_storage(self, tree: Dict[str, np.ndarray]) -> bool:
        """Allocate device rings for any new columns; returns False
        when the projection spilled this buffer to the host ring."""
        if self._host is not None:
            return False
        import jax
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib

        new_cols = {
            k: v for k, v in tree.items() if k not in self._store
        }
        if not new_cols:
            return True
        projected = self.storage_bytes + sum(
            self.capacity
            * int(np.prod(v.shape[1:]) if v.ndim > 1 else 1)
            * v.dtype.itemsize
            for v in new_cols.values()
        )
        cap = self._resolve_memory_cap()
        if cap is not None and projected > cap:
            # snapshot BEFORE arming the host fallback (get_state
            # delegates once _host is set)
            prior = self.get_state() if self._store else None
            self._host = self._make_host_fallback()
            if prior is not None:
                # columns arrived incrementally and the projection
                # only now tipped over: replay the resident rows into
                # the host ring so nothing is lost
                self._store, self._meta = {}, {}
                self._host.set_state(
                    {
                        "cols": prior["cols"],
                        "idx": prior["idx"],
                        "size": prior["size"],
                        "num_added": prior["num_added"],
                    }
                )
            self.storage_bytes = 0
            return False
        for k, v in new_cols.items():
            row_shape = tuple(v.shape[1:])
            packed = self._packable(row_shape, v.dtype)
            if packed:
                inner = int(np.prod(row_shape))
                ring = jnp.zeros(
                    (self.capacity, inner // 4), jnp.uint32
                )
            else:
                ring = jnp.zeros(
                    (self.capacity,) + row_shape, v.dtype
                )
            # rows shard over the data axis when capacity divides the
            # shard count, else replicate (specs.leaf_sharding rule);
            # put_global assembles cross-process shards when the mesh
            # spans hosts (fleet rings, docs/fleet.md) and is plain
            # device_put on a local mesh
            self._store[k] = sharding_lib.put_global(
                ring, sharding_lib.leaf_sharding(ring, self.mesh)
            )
            self._meta[k] = (row_shape, v.dtype, packed)
            self.storage_bytes += self.capacity * int(
                np.prod(row_shape) if row_shape else 1
            ) * v.dtype.itemsize
        self._insert_fn = None
        self._sample_fn = None
        return True

    def _resolve_pallas(self):
        """The per-program use_pallas value: explicit knob wins; auto
        (None) passes through to the kernels' own lowering probes,
        except on multi-device meshes where it resolves to False."""
        if self.use_pallas is not None:
            return bool(self.use_pallas)
        if self.pallas_interpret:
            return True
        if int(self.mesh.devices.size) != 1:
            return False
        return None

    def _build_insert_fn(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib
        from ray_tpu.ops import framestack as framestack_lib

        meta = dict(self._meta)
        up = self._resolve_pallas()
        interp = self.pallas_interpret

        def fn(store, rows, pos):
            out = dict(store)
            for k, v in rows.items():
                _, _, packed = meta[k]
                if packed:
                    v = jax.lax.bitcast_convert_type(
                        v.reshape(v.shape[0], -1, 4), jnp.uint32
                    )
                out[k] = framestack_lib.scatter_rows(
                    store[k], pos, v, use_pallas=up, interpret=interp
                )
            return out

        return sharding_lib.sharded_jit(
            fn,
            donate_argnums=(0,),
            label=f"replay_insert[{self.label}]",
        )

    def _build_sample_fn(self, row_sharded: bool):
        import jax
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib
        from ray_tpu.ops import framestack as framestack_lib

        meta = dict(self._meta)
        up = self._resolve_pallas()
        interp = self.pallas_interpret

        def fn(store, idx):
            out = {}
            for k, v in store.items():
                row_shape, dtype, packed = meta[k]
                g = framestack_lib.gather_rows(
                    v, idx, use_pallas=up, interpret=interp
                )
                if packed:
                    u8 = jax.lax.bitcast_convert_type(g, jnp.uint8)
                    g = u8.reshape((g.shape[0],) + row_shape)
                out[k] = g
            return out

        # explicit output placement: the learn programs declare
        # row-sharded batch inputs, and jit rejects (rather than
        # reshards) a committed mismatch — so the gather emits rows
        # already laid out for the nest; draws whose length doesn't
        # divide the shards (state snapshots) replicate instead
        out_spec = (
            sharding_lib.batch_sharded(self.mesh)
            if row_sharded
            else sharding_lib.replicated(self.mesh)
        )
        return sharding_lib.sharded_jit(
            fn,
            out_specs=out_spec,
            label=f"replay_sample[{self.label}]",
        )

    # -- ring bookkeeping (mirrors ReplayBuffer exactly) ----------------

    def __len__(self) -> int:
        if self._host is not None:
            return len(self._host)
        return self._size

    @property
    def num_added(self) -> int:
        if self._host is not None:
            return self._host.num_added
        return self._num_added

    def add(self, batch: SampleBatch) -> None:
        self.add_tree(
            {
                k: np.asarray(v)
                for k, v in batch.items()
                if isinstance(v, np.ndarray) and v.dtype != object
            }
        )

    def add_tree(self, tree: Dict[str, np.ndarray]) -> None:
        """Insert a host column tree (equal leading dims). This is the
        ONE host→device crossing of these rows."""
        tree = {
            k: self._canonical(np.ascontiguousarray(v))
            for k, v in tree.items()
        }
        if not tree:
            return
        n = int(next(iter(tree.values())).shape[0])
        if n == 0:
            return
        if not self._ensure_storage(tree):
            self._host.add(SampleBatch(tree))
            self._report_occupancy()
            return
        from ray_tpu import sharding as sharding_lib
        from ray_tpu.telemetry import metrics as telemetry_metrics

        telemetry_metrics.add_h2d_bytes(
            "replay_insert", sharding_lib.tree_nbytes(tree)
        )
        if self._insert_fn is None:
            self._insert_fn = self._build_insert_fn()
        pos = (self._idx + np.arange(n)) % self.capacity
        self._store = self._insert_fn(
            self._store, tree, pos.astype(np.int32)
        )
        self._idx = int((self._idx + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._num_added += n
        self._report_occupancy()

    def add_device_tree(self, tree: Dict[str, Any]) -> None:
        """Insert rows that are ALREADY device-resident (the jax
        rollout lane: in-program rollout rows — docs/pipeline.md).
        Zero H2D: the same donated scatter as :meth:`add_tree` runs on
        the resident columns. Ring bookkeeping, the host index
        generator, and (in the prioritized subclass) the sum-tree
        stream are EXACTLY the host insert's — inserting the same rows
        from either side leaves every subsequent ``sample()`` draw
        bit-identical (tests/test_jax_env.py). A spilled buffer pulls
        the rows back to its host ring (placement changes, sampling
        doesn't)."""
        tree = dict(tree)
        if not tree:
            return
        n = int(next(iter(tree.values())).shape[0])
        if n == 0:
            return
        if not self._ensure_storage(tree):
            import jax

            self._host.add(SampleBatch(jax.device_get(tree)))
            self._report_occupancy()
            return
        if self._insert_fn is None:
            self._insert_fn = self._build_insert_fn()
        pos = (self._idx + np.arange(n)) % self.capacity
        self._store = self._insert_fn(
            self._store, tree, pos.astype(np.int32)
        )
        self._idx = int((self._idx + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._num_added += n
        self._report_occupancy()

    def _report_occupancy(self) -> None:
        from ray_tpu.telemetry import metrics as telemetry_metrics

        telemetry_metrics.set_replay_occupancy(
            self.label,
            len(self),
            self.capacity,
            self.storage_bytes,
            device=self._host is None,
        )

    # -- sampling --------------------------------------------------------

    def sample(self, num_items: int):
        if self._host is not None:
            return self._host.sample(num_items)
        from ray_tpu.util import tracing

        with tracing.start_span("replay:sample", n=num_items):
            idx = self._rng.integers(0, self._size, num_items)
            return self.gather(idx)

    def _num_shards(self) -> int:
        from ray_tpu import sharding as sharding_lib

        return max(1, sharding_lib.num_shards(self.mesh))

    def gather(self, idx: np.ndarray) -> DeviceTrainBatch:
        """Rows at caller-chosen ring positions as one jit'd device
        gather (QMIX draws its own indices; ``sample`` feeds the
        host-seeded uniform draw through here)."""
        from ray_tpu.telemetry import metrics as telemetry_metrics

        idx = np.asarray(idx)
        row_sharded = len(idx) % self._num_shards() == 0 and len(idx) > 0
        if self._sample_fn is None:
            self._sample_fn = {}
        fn = self._sample_fn.get(row_sharded)
        if fn is None:
            fn = self._sample_fn[row_sharded] = self._build_sample_fn(
                row_sharded
            )
        idx32 = idx.astype(np.int32)
        # the index upload is the sample path's entire H2D payload
        # here (rows are resident); the device-tree draw even deletes
        # this — its indices never exist host-side
        telemetry_metrics.add_h2d_bytes("replay_sample", idx32.nbytes)
        tree = fn(self._store, idx32)
        return DeviceTrainBatch(dict(tree), len(idx), indices=idx)

    def draw_index_sets(self, k: int, num_items: int) -> np.ndarray:
        """Same draw discipline as the host ring (k sequential calls
        on the shared generator) — see ``ReplayBuffer
        .draw_index_sets``. Valid whether or not this buffer spilled
        (the generator object is shared with the spill ring)."""
        size = len(self)
        return np.stack(
            [
                self._rng.integers(0, size, num_items)
                for _ in range(k)
            ]
        )

    def superstep_feed(
        self,
        idx: np.ndarray,
        extra: Optional[Dict[str, np.ndarray]] = None,
    ) -> SuperstepRingFeed:
        """Package the device rings for an in-program superstep gather
        (``idx``: pre-drawn ``(k, B)`` positions; ``extra``: stacked
        host columns merged after the gather). The gather body is the
        sample path's — same uint32-lane unpack — so the scan consumes
        rows bit-identical to ``gather()``'s output."""
        if self._host is not None:
            raise RuntimeError(
                "superstep_feed on a spilled buffer — use the host "
                "stacked path"
            )
        import jax
        import jax.numpy as jnp

        if not isinstance(idx, jax.Array):
            idx = np.ascontiguousarray(idx, np.int32)
        meta = dict(self._meta)
        up = self._resolve_pallas()
        interp = self.pallas_interpret
        from ray_tpu.ops import framestack as framestack_lib

        def gather_fn(store, idx2):
            out = {}
            for k_, v in store.items():
                row_shape, _, packed = meta[k_]
                g = framestack_lib.gather_rows(
                    v, idx2, use_pallas=up, interpret=interp
                )
                if packed:
                    u8 = jax.lax.bitcast_convert_type(g, jnp.uint8)
                    g = u8.reshape(tuple(idx2.shape) + row_shape)
                out[k_] = g
            return out

        shardings = {k_: v.sharding for k_, v in self._store.items()}
        return SuperstepRingFeed(
            store=self._store,
            idx=idx,
            extra=dict(extra or {}),
            gather_fn=gather_fn,
            shardings=shardings,
            key=tuple(sorted(self._store)),
        )

    def stats(self) -> Dict:
        return {
            "size": len(self),
            "num_added": self.num_added,
            "device_resident": self._host is None,
            "storage_bytes": self.storage_bytes,
        }

    # -- checkpoint state ------------------------------------------------

    def get_state(self) -> Dict:
        if self._host is not None:
            state = self._host.get_state()
            state["spilled"] = True
            return state
        import jax

        host_store = jax.device_get(self._store)
        cols = {}
        for k, ring in host_store.items():
            row_shape, dtype, packed = self._meta[k]
            if packed:
                ring = (
                    ring.view(np.uint8)
                    .reshape((self.capacity,) + row_shape)
                )
            cols[k] = ring[: self._size].copy()
        return {
            "cols": cols,
            "idx": self._idx,
            "size": self._size,
            "num_added": self._num_added,
            "spilled": False,
        }

    def set_state(self, state: Dict) -> None:
        if state.get("spilled"):
            self._host = self._make_host_fallback()
            self._host.set_state(state)
            return
        cols = state["cols"]
        size = int(state["size"])
        full = {}
        for k, v in cols.items():
            ring = np.zeros(
                (self.capacity,) + v.shape[1:], v.dtype
            )
            ring[:size] = v
            full[k] = ring
        self._store, self._meta = {}, {}
        self.storage_bytes = 0
        if full and not self._ensure_storage(full):
            # restoring on a smaller-memory host: land in the spill
            # ring instead
            self._host.set_state(
                {k: state[k] for k in ("cols", "idx", "size", "num_added")}
            )
            return
        if full:
            if self._insert_fn is None:
                self._insert_fn = self._build_insert_fn()
            self._store = self._insert_fn(
                self._store,
                full,
                np.arange(self.capacity, dtype=np.int32),
            )
        self._idx = int(state["idx"])
        self._size = size
        self._num_added = int(state["num_added"])


class DevicePrioritizedReplayBuffer(_PrioritySampling, DeviceReplayBuffer):
    """Prioritized replay with device-resident rows. Two tree planes
    (docs/data_plane.md "device sum tree"):

    - ``device_tree=False`` (legacy): the sum/min trees (and every
      priority update) stay host-side — exactly the host
      :class:`PrioritizedReplayBuffer` code via ``_PrioritySampling``
      — while the drawn rows gather on device.
    - ``device_tree=True``: priorities live as f64 mesh arrays
      (``ops/segment_tree.DeviceSumTree``) and a sample is ONE fused
      program — prefix-descent draw → clip → IS weights → row gather
      — whose only host-fed input is the generator's raw uniform
      stream, so the index draws (and sampled priorities) reproduce
      the host trees bit-exactly and zero payload bytes cross H2D on
      the sample path. The alpha-power transform stays host-side
      (``powered_priorities`` — the one cross-backend-inexact op), so
      priority refreshes pull |td| D2H, power, and push powered
      leaves back; the tree WALK never returns to the host.

    IS weights ride into the batch tree as a device column;
    ``batch_indexes`` ride on the returned :class:`DeviceTrainBatch`
    (host numpy under the host tree, a device i32 array under the
    device tree — ``update_priorities`` accepts either)."""

    def __init__(
        self,
        capacity: int = 10000,
        alpha: float = 0.6,
        seed: Optional[int] = None,
        mesh=None,
        memory_cap_bytes: Optional[int] = None,
        label: str = "default_policy",
        device_tree: bool = False,
    ):
        super().__init__(
            capacity,
            seed,
            mesh=mesh,
            memory_cap_bytes=memory_cap_bytes,
            label=label,
        )
        self._init_priority_trees(capacity, alpha)
        self._dtree = None
        self._tree_sample_fns: Dict = {}
        self._tree_draw_fns: Dict = {}
        if device_tree:
            from ray_tpu.ops.segment_tree import DeviceSumTree

            self._dtree = DeviceSumTree(
                self._tree_capacity, mesh=self.mesh, label=label
            )

    @property
    def tree_plane(self) -> str:
        """Which tree implementation serves draws right now (the
        ``tree`` label of ``info/telemetry/replay``)."""
        if self._host is not None or self._dtree is None:
            return "host"
        return "device"

    def _make_host_fallback(self) -> ReplayBuffer:
        buf = PrioritizedReplayBuffer(self.capacity, self._alpha)
        buf._rng = self._rng
        if self._dtree is not None:
            # the spill rings own the priorities from here on: pull
            # the (usually still pristine) device leaves across once
            buf._set_priority_state(
                {
                    "leaf_values": self._dtree.leaf_values(self._size),
                    "max_priority": self._max_priority,
                }
            )
            self._dtree = None
            self._tree_sample_fns = {}
            self._tree_draw_fns = {}
            return buf
        # spill happens at first insert, before any priority write:
        # handing over the (still pristine) trees keeps one source of
        # truth if callers pre-seeded priorities
        buf._sum_tree = self._sum_tree
        buf._min_tree = self._min_tree
        buf._max_priority = self._max_priority
        return buf

    # -- device-tree priority writes ------------------------------------

    def update_priorities(
        self, idx, priorities: np.ndarray
    ) -> None:
        """Host-tree mode: the mixin's numpy tree writes. Device-tree
        mode: host alpha-power (the oracle transform), then one
        donated device update program; ``idx`` may be a host array or
        the device i32 indices a fused sample returned (no D2H)."""
        if self._dtree is None:
            return _PrioritySampling.update_priorities(
                self, idx, priorities
            )
        from ray_tpu.telemetry import metrics as telemetry_metrics

        powered, clamped = powered_priorities(priorities, self._alpha)
        self._dtree.set_powered(idx, powered)
        self._max_priority = max(
            self._max_priority, float(clamped.max())
        )
        telemetry_metrics.inc_tree_op(self._tree_op, "device")

    def refresh_priorities_stacked(
        self, idx, abs_td: np.ndarray, active
    ) -> None:
        """The superstep's PER refresh against the device tree: the
        stacked ``(k, B)`` |td| (one D2H — the host alpha-power needs
        it) powers host-side and lands in ONE stacked device update,
        applied in update order with the nan-guard's skipped slots
        masked out — exactly the host path's per-update
        ``update_priorities(idx[i], td[i] + 1e-6)`` loop."""
        from ray_tpu.telemetry import metrics as telemetry_metrics

        active = np.asarray(active, bool)
        if not active.any():
            return
        # the epsilon add stays in the |td| dtype (f32): the host call
        # site computes `pri[i] + 1e-6` under numpy's weak-scalar
        # promotion BEFORE the f64 cast inside update_priorities —
        # rounding it the same way here keeps the leaf stream (and the
        # max-priority watermark) bit-exact across tree planes
        powered, clamped = powered_priorities(
            np.asarray(abs_td) + 1e-6, self._alpha
        )
        if self._dtree is None:
            # spilled mid-superstep is impossible (feed construction
            # requires residency), but route host-tree mode through
            # the sequential oracle writes for completeness
            for i in range(len(active)):
                if active[i]:
                    _PrioritySampling.update_priorities(
                        self, np.asarray(idx)[i], abs_td[i] + 1e-6
                    )
            return
        self._dtree.set_powered(idx, powered, active=active)
        self._max_priority = max(
            self._max_priority, float(clamped[active].max())
        )
        telemetry_metrics.inc_tree_op(
            "update", "device", int(active.sum())
        )

    def _insert_priorities(self, idx, priorities) -> None:
        self._tree_op = "insert"
        try:
            self.update_priorities(
                idx, np.asarray(priorities, np.float64)
            )
        finally:
            self._tree_op = "update"

    def add_tree(
        self,
        tree: Dict[str, np.ndarray],
        priorities: Optional[np.ndarray] = None,
    ) -> None:
        if not tree:
            return
        n = int(next(iter(tree.values())).shape[0])
        if n == 0:
            return
        if priorities is None:
            priorities = np.full(n, self._max_priority)
        if self._host is not None:
            self._host.add_with_priorities(
                SampleBatch(tree), priorities
            )
            self._report_occupancy()
            return
        idx = (self._idx + np.arange(n)) % self.capacity
        DeviceReplayBuffer.add_tree(self, tree)
        if self._host is not None:  # this insert triggered the spill
            self._host.update_priorities(
                idx, np.asarray(priorities, np.float64)
            )
            return
        self._insert_priorities(idx, priorities)

    def add_device_tree(
        self,
        tree: Dict[str, Any],
        priorities: Optional[np.ndarray] = None,
    ) -> None:
        """Device-resident insert with the host priority protocol:
        new rows enter the sum/min trees at max priority (or the
        caller's), exactly like :meth:`add_tree` — the priority
        stream stays bit-exact whichever side the rows came from."""
        tree = dict(tree)
        if not tree:
            return
        n = int(next(iter(tree.values())).shape[0])
        if n == 0:
            return
        if priorities is None:
            priorities = np.full(n, self._max_priority)
        if self._host is not None:
            import jax

            self._host.add_with_priorities(
                SampleBatch(jax.device_get(tree)), priorities
            )
            self._report_occupancy()
            return
        idx = (self._idx + np.arange(n)) % self.capacity
        DeviceReplayBuffer.add_device_tree(self, tree)
        if self._host is not None:  # this insert triggered the spill
            self._host.update_priorities(
                idx, np.asarray(priorities, np.float64)
            )
            return
        self._insert_priorities(idx, priorities)

    # -- sampling --------------------------------------------------------

    def _build_tree_sample_fn(self, num_items: int, row_sharded: bool):
        """ONE program: prefix-descent draw → clip → IS weights → row
        gather (docs/data_plane.md "device sum tree"). Built and
        called in the f64 scope (the tree inputs); rows/weights leave
        as the learner's f32/u8 world with the same out-shardings the
        two-step path emitted."""
        import jax
        import jax.numpy as jnp

        from ray_tpu import sharding as sharding_lib
        from ray_tpu.ops.segment_tree import draw_body

        meta = dict(self._meta)
        cap = self._dtree.capacity

        def fn(sum_t, min_t, store, rand, size, beta):
            idx, weights, _ = draw_body(
                sum_t, min_t, rand, size, beta, cap
            )
            idx32 = idx.astype(jnp.int32)
            out = {}
            for k, v in store.items():
                row_shape, dtype, packed = meta[k]
                g = v[idx32]
                if packed:
                    u8 = jax.lax.bitcast_convert_type(g, jnp.uint8)
                    g = u8.reshape((g.shape[0],) + row_shape)
                out[k] = g
            out["weights"] = weights
            return out, idx32

        row_spec = (
            sharding_lib.batch_sharded(self.mesh)
            if row_sharded
            else sharding_lib.replicated(self.mesh)
        )
        rep = sharding_lib.replicated(self.mesh)
        out_cols = {k: row_spec for k in meta}
        out_cols["weights"] = row_spec
        return sharding_lib.sharded_jit(
            fn,
            out_specs=(out_cols, rep),
            label=f"replay_draw_sample[{self.label}:{num_items}]",
        )

    def _tree_sample(self, num_items: int, beta: float):
        from ray_tpu import sharding as sharding_lib
        from ray_tpu.telemetry import metrics as telemetry_metrics

        rand = self._rng.random(num_items)
        row_sharded = (
            num_items % self._num_shards() == 0 and num_items > 0
        )
        key = (num_items, row_sharded)
        fn = self._tree_sample_fns.get(key)
        if fn is None:
            fn = self._tree_sample_fns[key] = (
                self._build_tree_sample_fn(num_items, row_sharded)
            )
        with sharding_lib.f64_scope():
            rows, idx = fn(
                self._dtree.sum_value,
                self._dtree.min_value,
                self._store,
                rand,
                np.int64(self._size),
                np.float64(beta),
            )
        # the generator's raw uniform stream is the draw's only
        # host-fed input — counted apart from payload, which is zero
        telemetry_metrics.add_h2d_bytes("replay_rng", rand.nbytes)
        telemetry_metrics.inc_tree_op("sample", "device")
        return DeviceTrainBatch(dict(rows), num_items, indices=idx)

    def sample(self, num_items: int, beta: float = 0.4):
        if self._host is not None:
            return self._host.sample(num_items, beta=beta)
        from ray_tpu.util import tracing

        with tracing.start_span(
            "replay:sample", n=num_items, tree=self.tree_plane
        ):
            if self._dtree is not None:
                return self._tree_sample(num_items, beta)
            import jax

            from ray_tpu import sharding as sharding_lib
            from ray_tpu.telemetry import metrics as telemetry_metrics

            idx, weights = self._draw_prioritized(num_items, beta)
            batch = self.gather(idx)
            # same layout as the gathered rows, so the learn program's
            # committed-input check sees one consistent batch tree
            spec = (
                sharding_lib.batch_sharded(self.mesh)
                if num_items % self._num_shards() == 0
                else sharding_lib.replicated(self.mesh)
            )
            telemetry_metrics.add_h2d_bytes(
                "replay_sample", weights.nbytes
            )
            batch.tree["weights"] = jax.device_put(weights, spec)
            return batch

    def draw_prioritized_sets_device(
        self, k: int, k_max: int, num_items: int, beta: float
    ):
        """The superstep's pre-drawn schedule against the DEVICE tree:
        ``k`` sequential host generator calls (the exact per-update
        stream order), padded host-side to ``k_max`` rows, one draw
        program → ``(k_max, B)`` device index/weight matrices laid out
        for the scan feed (indices replicated, weights row-sharded
        like every stacked extra column). Draws see window-start
        priorities — the documented within-chain staleness."""
        from ray_tpu import sharding as sharding_lib
        from ray_tpu.telemetry import metrics as telemetry_metrics

        rand = np.zeros((k_max, num_items), np.float64)
        for i in range(k):
            rand[i] = self._rng.random(num_items)
        key = (k_max, num_items)
        fn = self._tree_draw_fns.get(key)
        if fn is None:
            import jax.numpy as jnp

            from ray_tpu.ops.segment_tree import draw_body

            cap = self._dtree.capacity

            def prog(sum_t, min_t, r, size, beta_):
                idx, weights, _ = draw_body(
                    sum_t, min_t, r, size, beta_, cap
                )
                return idx.astype(jnp.int32), weights

            fn = self._tree_draw_fns[key] = sharding_lib.sharded_jit(
                prog,
                out_specs=(
                    sharding_lib.replicated(self.mesh),
                    sharding_lib.batch_sharded(
                        self.mesh, ndim_prefix=2
                    ),
                ),
                label=f"tree_draw_sets[{self.label}:{k_max}x{num_items}]",
            )
        with sharding_lib.f64_scope():
            idx, weights = fn(
                self._dtree.sum_value,
                self._dtree.min_value,
                rand,
                np.int64(self._size),
                np.float64(beta),
            )
        telemetry_metrics.add_h2d_bytes(
            "replay_rng", k * num_items * 8
        )
        telemetry_metrics.inc_tree_op("sample", "device", k)
        return idx, weights

    def get_state(self) -> Dict:
        state = super().get_state()
        if self._host is None:
            state["priorities"] = self._priority_state()
        return state

    def set_state(self, state: Dict) -> None:
        super().set_state(state)
        if "priorities" in state and self._host is None:
            self._set_priority_state(state["priorities"])

    def _priority_state(self) -> Dict:
        if self._dtree is None:
            return _PrioritySampling._priority_state(self)
        # same layout as the host trees' state: checkpoints move
        # freely between tree planes
        return {
            "leaf_values": self._dtree.leaf_values(self._size),
            "max_priority": self._max_priority,
        }

    def _set_priority_state(self, state: Dict) -> None:
        if self._dtree is None:
            return _PrioritySampling._set_priority_state(self, state)
        self._dtree.set_leaf_values(state["leaf_values"])
        self._max_priority = float(state.get("max_priority", 1.0))


class MultiAgentReplayBuffer:
    """Per-policy buffers (reference multi_agent_replay_buffer.py).

    ``device_resident=True`` stores each policy's rows on the learner
    mesh (:class:`DeviceReplayBuffer`); ``replay_columns_fn(pid,
    SampleBatch) -> dict`` converts fragments to the column tree the
    policy's learn program consumes (``JaxPolicy.replay_columns``) —
    applied ONCE at insert, so sampled batches feed
    ``learn_on_device_batch`` with zero further host work."""

    def __init__(
        self,
        capacity: int = 10000,
        prioritized: bool = False,
        alpha: float = 0.6,
        seed: Optional[int] = None,
        device_resident: bool = False,
        mesh=None,
        memory_cap_bytes: Optional[int] = None,
        replay_columns_fn: Optional[Callable] = None,
        device_tree: bool = False,
    ):
        self.capacity = capacity
        self.prioritized = prioritized
        self.alpha = alpha
        self.seed = seed
        self.device_resident = device_resident
        self.mesh = mesh
        self.memory_cap_bytes = memory_cap_bytes
        self.replay_columns_fn = replay_columns_fn
        self.device_tree = device_tree
        self.buffers: Dict[str, ReplayBuffer] = {}

    def _buffer(self, pid: str) -> ReplayBuffer:
        if pid not in self.buffers:
            if self.device_resident:
                cls = (
                    DevicePrioritizedReplayBuffer
                    if self.prioritized
                    else DeviceReplayBuffer
                )
                kwargs = dict(
                    mesh=self.mesh,
                    memory_cap_bytes=self.memory_cap_bytes,
                    label=pid,
                )
                if self.prioritized:
                    self.buffers[pid] = cls(
                        self.capacity,
                        self.alpha,
                        self.seed,
                        device_tree=self.device_tree,
                        **kwargs,
                    )
                else:
                    self.buffers[pid] = cls(
                        self.capacity, self.seed, **kwargs
                    )
            elif self.prioritized:
                self.buffers[pid] = PrioritizedReplayBuffer(
                    self.capacity, self.alpha, self.seed
                )
            else:
                self.buffers[pid] = ReplayBuffer(self.capacity, self.seed)
        return self.buffers[pid]

    def add(self, batch) -> None:
        from ray_tpu.data.sample_batch import (
            DEFAULT_POLICY_ID,
            MultiAgentBatch,
        )

        if isinstance(batch, SampleBatch):
            batch = batch.as_multi_agent()
        for pid, sb in batch.policy_batches.items():
            buf = self._buffer(pid)
            if isinstance(buf, DeviceReplayBuffer):
                if self.replay_columns_fn is not None:
                    tree = self.replay_columns_fn(pid, sb)
                else:
                    tree = {
                        k: np.asarray(v)
                        for k, v in sb.items()
                        if isinstance(v, np.ndarray)
                        and v.dtype != object
                    }
                buf.add_tree(tree)
            else:
                buf.add(sb)

    def add_device_tree(
        self, tree: Dict[str, Any], policy_id: Optional[str] = None
    ) -> None:
        """Device-resident insert for the jax rollout lane: rows from
        an in-program rollout land in ``policy_id``'s buffer without
        touching the host. Requires ``device_resident=True`` (a host
        ring can't absorb device rows without the very D2H round trip
        this path exists to avoid)."""
        from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID

        buf = self._buffer(policy_id or DEFAULT_POLICY_ID)
        if not isinstance(buf, DeviceReplayBuffer):
            raise TypeError(
                "add_device_tree needs a device-resident buffer "
                "(config replay_device_resident)"
            )
        buf.add_device_tree(tree)

    def sample(self, num_items: int, **kwargs):
        from ray_tpu.data.sample_batch import MultiAgentBatch

        out = {}
        for pid, buf in self.buffers.items():
            if len(buf) >= num_items:
                out[pid] = (
                    buf.sample(num_items, **kwargs)
                    if isinstance(
                        buf,
                        (
                            PrioritizedReplayBuffer,
                            DevicePrioritizedReplayBuffer,
                        ),
                    )
                    else buf.sample(num_items)
                )
        return MultiAgentBatch(out, num_items)

    def __len__(self) -> int:
        return max((len(b) for b in self.buffers.values()), default=0)

    def get_state(self) -> Dict:
        """Per-policy buffer states, checkpointable through
        ``Algorithm.save_checkpoint`` (all arrays host numpy — device
        rings are pulled back and re-uploaded on restore)."""
        return {pid: b.get_state() for pid, b in self.buffers.items()}

    def set_state(self, state: Dict) -> None:
        for pid, s in state.items():
            self._buffer(pid).set_state(s)
