"""Async request management for rollout-worker fleets.

Counterpart of the reference's ``rllib/execution/parallel_requests.py``
(``AsyncRequestsManager`` / ``asynchronous_parallel_requests``), the host
half of the sampling pipeline: keep up to
``max_remote_requests_in_flight_per_worker`` requests outstanding per
actor, harvest completions with ``ray.wait`` (stragglers stop gating the
round — fast workers' results flow as they land), and tolerate dead
actors by dropping them from the rotation and reporting, never raising.

The device half of the pipeline already exists (``DeviceFeeder`` /
``LearnerThread``); ``rollout_ops.SamplePrefetcher`` joins the two so
batch k+1 is collected, concatenated and transferred while the SGD nest
runs batch k.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as ray
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing

# Actor-fatal errors: the worker is gone, its pending results with it.
_ACTOR_DEAD_ERRORS = (
    ray.core.object_store.RayActorError,
    ray.core.object_store.WorkerCrashedError,
)


def _default_remote_fn(worker):
    return worker.sample.remote()


class AsyncRequestsManager:
    """Tracks in-flight remote requests across a set of actors
    (reference parallel_requests.py:24).

    - ``submit`` / ``submit_available`` enforce the per-worker in-flight
      cap, so a slow worker never accumulates an unbounded request queue.
    - ``get_ready`` harvests with ``ray.wait``: it blocks (up to
      ``timeout``) only until ``min_results`` requests complete, then
      sweeps everything else already done — completion order, not
      submission order.
    - A worker whose harvested ref raises an actor-fatal error is moved
      to the dead list (``take_dead_workers``) and drops out of the
      submission rotation; the caller decides whether to recreate it.
      Application errors (``RayTaskError``) still raise — a bug in
      ``sample()`` must not be silently eaten.
    """

    def __init__(
        self,
        workers: Optional[List] = None,
        *,
        max_remote_requests_in_flight_per_worker: int = 2,
        return_object_refs: bool = False,
        name: str = "default",
        retry_policy=None,
    ):
        self._max_in_flight = int(max_remote_requests_in_flight_per_worker)
        self._return_refs = bool(return_object_refs)
        # uniform timeout/backoff schedule (docs/resilience.md): bounds
        # the blocking harvest wait when the caller didn't pass one and
        # retries transient submission faults
        self._retry = retry_policy
        # telemetry tag: several managers coexist per process (sync
        # sampler rounds, PPO prefetcher, IMPALA polling) — the name
        # keeps their in-flight / dead-worker series apart
        self.name = name
        self._workers: List = []
        self._in_flight: Dict = {}  # ref -> worker
        self._counts: Dict[int, int] = {}  # id(worker) -> outstanding
        self._dead: List = []  # observed-dead, not yet reported
        self._dead_ids: set = set()  # id() of every worker ever seen dead
        self.num_completed = 0
        self.num_dropped = 0  # results lost to dead workers
        for w in workers or []:
            self.add_workers([w])

    # -- fleet membership ------------------------------------------------

    def add_workers(self, workers: List) -> None:
        for w in workers:
            if w not in self._workers:
                self._workers.append(w)
                # RESET, not setdefault: a recreated actor handle can
                # reuse a freed id(), and the corpse's leftover
                # in-flight count would cap the new worker at zero
                # submission slots forever
                self._counts[id(w)] = 0
                # ...and its stale dead-mark would suppress the
                # report-once protocol, so a death of the NEW worker
                # would never reach take_dead_workers (the dead-workers
                # metric stays honest: one increment per death, counted
                # again if the re-added worker dies again)
                self._dead_ids.discard(id(w))
                self._dead = [d for d in self._dead if d is not w]

    def remove_workers(
        self, workers: List, *, drop_in_flight: bool = False
    ) -> int:
        """Stop submitting to ``workers``. By default their in-flight
        refs stay tracked so completions (or errors) still drain
        through ``get_ready``; with ``drop_in_flight`` the refs are
        explicitly dropped and freed instead — scale-down semantics:
        every outstanding request is either harvested or dropped, never
        leaked into the in-flight gauge. Returns the number of refs
        dropped."""
        drop = {id(w) for w in workers}
        self._workers = [w for w in self._workers if id(w) not in drop]
        if not drop_in_flight:
            return 0
        return self._drop_refs(drop)

    def _drop_refs(self, worker_ids: set, pending_only: bool = False) -> int:
        """Drop (and free) in-flight refs belonging to ``worker_ids``.
        ``pending_only`` keeps refs that already completed — their
        results are in the object store and harvest normally even
        after the worker process is gone."""
        victims = [
            ref
            for ref, w in self._in_flight.items()
            if id(w) in worker_ids
        ]
        if pending_only and victims:
            ready, _ = ray.wait(
                victims, num_returns=len(victims), timeout=0
            )
            done = {r.id for r in ready}
            victims = [r for r in victims if r.id not in done]
        dropped = 0
        for ref in victims:
            w = self._in_flight.pop(ref)
            wid = id(w)
            self._counts[wid] = max(0, self._counts.get(wid, 1) - 1)
            self.num_dropped += 1
            dropped += 1
            if not self._return_refs:
                try:
                    ray.free([ref])
                except Exception:
                    pass
        if dropped:
            telemetry_metrics.set_requests_in_flight(
                self.name, len(self._in_flight)
            )
        return dropped

    def retire_worker(self, worker) -> int:
        """Planned scale-down exit (drain or reap — docs/resilience.md):
        take ``worker`` out of rotation, keep its COMPLETED in-flight
        results for the normal harvest (they're already in the object
        store), explicitly drop-and-free the still-pending ones, and
        suppress any later death report — a drained worker observed
        dead after its planned exit must not re-enter the failure
        protocol as a casualty. Returns the number of dropped refs."""
        self._workers = [w for w in self._workers if w is not worker]
        dropped = self._drop_refs({id(worker)}, pending_only=True)
        # pre-mark dead WITHOUT queuing a report: _mark_dead's
        # report-once check sees the id and stays silent if the killed
        # process later surfaces an actor-death error on a leftover ref
        self._dead_ids.add(id(worker))
        self._dead = [d for d in self._dead if d is not worker]
        return dropped

    def workers(self) -> List:
        return list(self._workers)

    def take_dead_workers(self) -> List:
        """Workers observed dead since the last call (report-once)."""
        dead, self._dead = self._dead, []
        return dead

    # -- submission ------------------------------------------------------

    def in_flight(self, worker=None) -> int:
        if worker is not None:
            return self._counts.get(id(worker), 0)
        return len(self._in_flight)

    def submit(
        self,
        remote_fn: Optional[Callable] = None,
        *,
        worker=None,
    ) -> bool:
        """Launch ``remote_fn(worker)`` (default ``sample.remote()``) if
        the worker is live and under its in-flight cap. With no
        ``worker``, picks the least-loaded live worker with a free slot.
        Returns False when nothing could be submitted."""
        remote_fn = remote_fn or _default_remote_fn
        if worker is None:
            candidates = [
                w
                for w in self._workers
                if self._counts.get(id(w), 0) < self._max_in_flight
            ]
            if not candidates:
                return False
            worker = min(
                candidates, key=lambda w: self._counts.get(id(w), 0)
            )
        elif (
            worker not in self._workers
            or self._counts.get(id(worker), 0) >= self._max_in_flight
        ):
            return False
        try:
            if self._retry is not None:
                # transient submission faults (timeouts, transport
                # hiccups) retry on the uniform backoff schedule;
                # actor-death is NOT retryable and falls through
                ref = self._retry.call(lambda: remote_fn(worker))
            else:
                ref = remote_fn(worker)
        except _ACTOR_DEAD_ERRORS:
            # the runtime can reject submission to an actor it already
            # knows is dead — same drop-and-report path as a harvested
            # death
            self._mark_dead(worker)
            return False
        self._in_flight[ref] = worker
        self._counts[id(worker)] = self._counts.get(id(worker), 0) + 1
        return True

    def submit_available(
        self, remote_fn: Optional[Callable] = None
    ) -> int:
        """Saturate every live worker up to the in-flight cap."""
        t0 = time.time()
        n = 0
        for w in list(self._workers):
            while self.submit(remote_fn, worker=w):
                n += 1
        if n:
            telemetry_metrics.set_requests_in_flight(
                self.name, len(self._in_flight)
            )
            tracing.record_span(
                "requests:submit",
                t0,
                time.time(),
                manager=self.name,
                submitted=n,
                in_flight=len(self._in_flight),
            )
        return n

    # -- harvest ---------------------------------------------------------

    def get_ready(
        self,
        *,
        timeout: Optional[float] = None,
        min_results: int = 1,
    ) -> Dict[Any, List]:
        """Harvest completed requests → ``{worker: [result, ...]}``.

        Blocks up to ``timeout`` (None = indefinitely) for the first
        ``min_results`` completions, then sweeps everything else already
        ready without blocking. Dead workers are dropped and recorded;
        in value mode the harvested refs are freed."""
        t_harvest0 = time.time()
        refs = list(self._in_flight.keys())
        if not refs:
            return {}
        if timeout is None and self._retry is not None:
            # an indefinite wait against a wedged actor is the hang
            # the resilience layer exists to prevent: bound it by the
            # policy's per-attempt timeout (callers see an empty
            # harvest and re-poll, exactly like an explicit timeout)
            timeout = self._retry.timeout_s
        if timeout is None or timeout > 0:
            ray.wait(
                refs,
                num_returns=min(max(1, min_results), len(refs)),
                timeout=timeout,
            )
        # sweep: one non-blocking scan picks up every completion
        ready, _ = ray.wait(refs, num_returns=len(refs), timeout=0)
        out: Dict[Any, List] = {}
        for ref in ready:
            worker = self._in_flight.pop(ref)
            wid = id(worker)
            self._counts[wid] = max(0, self._counts.get(wid, 1) - 1)
            if self._return_refs:
                out.setdefault(worker, []).append(ref)
                self.num_completed += 1
                continue
            try:
                result = ray.get(ref)
            except _ACTOR_DEAD_ERRORS:
                self._mark_dead(worker)
                continue
            finally:
                ray.free([ref])
            out.setdefault(worker, []).append(result)
            self.num_completed += 1
        if ready:
            telemetry_metrics.set_requests_in_flight(
                self.name, len(self._in_flight)
            )
            tracing.record_span(
                "requests:harvest",
                t_harvest0,
                time.time(),
                manager=self.name,
                harvested=len(ready),
                workers=len(out),
                in_flight=len(self._in_flight),
            )
        return out

    def report_dead(self, worker) -> None:
        """Caller-observed death (refs mode surfaces actor errors at the
        caller's ``ray.get``/marshal, not inside the manager): drop the
        worker from rotation and queue it for ``take_dead_workers``."""
        self._mark_dead(worker)

    def _mark_dead(self, worker) -> None:
        self.num_dropped += 1
        self.remove_workers([worker])
        if id(worker) not in self._dead_ids:
            self._dead_ids.add(id(worker))
            self._dead.append(worker)
            telemetry_metrics.inc_dead_workers(self.name)
            tracing.event(
                "worker:dead",
                manager=self.name,
                live_workers=len(self._workers),
            )

    def stats(self) -> Dict[str, int]:
        return {
            "num_requests_in_flight": len(self._in_flight),
            "num_completed": self.num_completed,
            "num_dropped_dead_worker": self.num_dropped,
            "num_live_workers": len(self._workers),
        }


def asynchronous_parallel_requests(
    manager: AsyncRequestsManager,
    *,
    remote_fn: Optional[Callable] = None,
    timeout: Optional[float] = 0.1,
    min_results: int = 1,
) -> Dict[Any, List]:
    """One poll round of the async sampling loop (reference
    ``asynchronous_parallel_requests``): top every live worker up to its
    in-flight cap, then harvest whatever has completed. IMPALA/APPO's
    worker polling and the PPO prefetch thread both run on this."""
    manager.submit_available(remote_fn)
    return manager.get_ready(timeout=timeout, min_results=min_results)


def wait_asynchronous_requests(
    manager: AsyncRequestsManager,
    *,
    deadline_s: float,
    min_results: int = 1,
) -> Dict[Any, List]:
    """``get_ready`` with an absolute patience budget: re-polls until at
    least ``min_results`` arrive or ``deadline_s`` elapses (dead workers
    can make a single ``ray.wait`` return early with nothing)."""
    t0 = time.monotonic()
    out: Dict[Any, List] = {}
    got = 0
    while True:
        remaining = deadline_s - (time.monotonic() - t0)
        ready = manager.get_ready(
            timeout=max(0.0, remaining), min_results=min_results - got
        )
        for w, results in ready.items():
            out.setdefault(w, []).extend(results)
            got += len(results)
        if got >= min_results or remaining <= 0:
            return out
        if not manager.in_flight():
            return out
