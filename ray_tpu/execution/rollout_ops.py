"""Rollout collection primitives.

Counterpart of the reference's ``rllib/execution/rollout_ops.py:35``
(synchronous_parallel_sample).
"""

from __future__ import annotations

from typing import List, Optional, Union

import ray_tpu as ray
from ray_tpu.data.sample_batch import (
    MultiAgentBatch,
    SampleBatch,
    concat_samples,
)


def synchronous_parallel_sample(
    *,
    worker_set,
    max_agent_steps: Optional[int] = None,
    max_env_steps: Optional[int] = None,
    concat: bool = True,
) -> Union[SampleBatch, MultiAgentBatch, List]:
    """Sample from all workers in parallel until the step target is met
    (reference rollout_ops.py:35)."""
    agent_or_env_steps = 0
    max_steps = max_agent_steps or max_env_steps
    all_batches = []
    while True:
        if worker_set.num_remote_workers() <= 0:
            batches = [worker_set.local_worker().sample()]
        else:
            refs = [
                w.sample.remote() for w in worker_set.remote_workers()
            ]
            batches = ray.get(refs)
        for b in batches:
            if max_agent_steps:
                agent_or_env_steps += (
                    b.agent_steps()
                    if isinstance(b, MultiAgentBatch)
                    else b.count
                )
            else:
                agent_or_env_steps += b.env_steps()
        all_batches.extend(batches)
        if max_steps is None or agent_or_env_steps >= max_steps:
            break
    if concat:
        return concat_samples(all_batches)
    return all_batches
