"""Rollout collection primitives.

Counterpart of the reference's ``rllib/execution/rollout_ops.py:35``
(synchronous_parallel_sample), rebuilt on the shared
:class:`~ray_tpu.execution.parallel_requests.AsyncRequestsManager` so the
synchronous and pipelined paths drive workers through one mechanism.

``SamplePrefetcher`` is the host half of the PPO pipeline
(``config.sample_prefetch``): a thread keeps every rollout worker
saturated with ``sample.remote`` calls, harvests fragments in completion
order, concatenates them into train batches and hands the prepared host
tree to a ``DeviceFeeder`` — so batch k+1's collection, concat AND
host→device transfer all overlap the jitted SGD nest of batch k.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional, Union

import ray_tpu as ray
from ray_tpu.data.sample_batch import (
    MultiAgentBatch,
    SampleBatch,
    concat_samples,
)
from ray_tpu.execution.parallel_requests import AsyncRequestsManager
from ray_tpu.util import tracing


def synchronous_parallel_sample(
    *,
    worker_set,
    max_agent_steps: Optional[int] = None,
    max_env_steps: Optional[int] = None,
    concat: bool = True,
) -> Union[SampleBatch, MultiAgentBatch, List]:
    """Sample from all workers in parallel until the step target is met
    (reference rollout_ops.py:35).

    Round semantics are unchanged from the bare-``ray.get`` loop — one
    request per worker per round, batches ordered by worker index — so
    fixed-seed results are bit-identical; but the round is harvested
    with ``ray.wait`` through the request manager, so completions are
    accounted as they land and an actor-death error surfaces only after
    the healthy workers' results arrived (it still raises: the
    synchronous algorithms' recreate/ignore protocol relies on it)."""
    agent_or_env_steps = 0
    max_steps = max_agent_steps or max_env_steps
    all_batches = []
    with tracing.start_span("sample:round") as span:
        if worker_set.num_remote_workers() <= 0:
            while True:
                batches = [worker_set.local_worker().sample()]
                agent_or_env_steps += _count_steps(
                    batches, max_agent_steps
                )
                all_batches.extend(batches)
                if (
                    max_steps is None
                    or agent_or_env_steps >= max_steps
                ):
                    break
            span.set_attribute("steps", agent_or_env_steps)
            return (
                concat_samples(all_batches) if concat else all_batches
            )

        workers = worker_set.remote_workers()
        order = {id(w): i for i, w in enumerate(workers)}
        manager = AsyncRequestsManager(
            workers,
            max_remote_requests_in_flight_per_worker=1,
            name="sync_sample",
            retry_policy=getattr(worker_set, "retry_policy", None),
        )
        while True:
            manager.submit_available()
            round_results = []  # (worker_index, batch)
            while manager.in_flight():
                for w, results in manager.get_ready(
                    timeout=5.0
                ).items():
                    for b in results:
                        round_results.append((order[id(w)], b))
            if manager.take_dead_workers():
                # preserve the seed protocol: a dead worker aborts the
                # sample and raises, so Algorithm.step can
                # recreate/ignore
                raise ray.core.object_store.RayActorError(
                    "rollout worker died during "
                    "synchronous_parallel_sample"
                )
            batches = [
                b
                for _, b in sorted(round_results, key=lambda x: x[0])
            ]
            agent_or_env_steps += _count_steps(
                batches, max_agent_steps
            )
            all_batches.extend(batches)
            if max_steps is None or agent_or_env_steps >= max_steps:
                break
        span.set_attribute("steps", agent_or_env_steps)
        span.set_attribute("workers", len(workers))
    if concat:
        return concat_samples(all_batches)
    return all_batches


def _count_steps(batches, by_agent_steps) -> int:
    n = 0
    for b in batches:
        if by_agent_steps:
            n += (
                b.agent_steps()
                if isinstance(b, MultiAgentBatch)
                else b.count
            )
        else:
            n += b.env_steps()
    return n


class SamplePrefetcher:
    """Background sampling pipeline for on-policy prefetch
    (``config.sample_prefetch``).

    A daemon thread runs the async poll loop: saturate every rollout
    worker (``max_in_flight`` outstanding requests each), harvest
    fragments in completion order, accumulate to ``target_steps``, then
    ``concat_samples`` and hand the batch to ``deliver`` — typically
    standardize + ``policy.prepare_batch`` + ``DeviceFeeder.put``, whose
    bounded queues provide the backpressure that bounds staleness (see
    docs/pipeline.md). Dead workers are dropped and reported via
    :meth:`take_dead_workers`; the pipeline keeps running on the
    survivors. A pipeline-thread exception parks in :attr:`error` and
    stops the thread instead of vanishing."""

    def __init__(
        self,
        worker_set,
        *,
        target_steps: int,
        deliver: Callable[[SampleBatch], None],
        max_in_flight: int = 2,
        poll_timeout_s: float = 0.2,
    ):
        self._manager = AsyncRequestsManager(
            worker_set.remote_workers(),
            max_remote_requests_in_flight_per_worker=max_in_flight,
            name="sample_prefetcher",
            retry_policy=getattr(worker_set, "retry_policy", None),
        )
        self._target = int(target_steps)
        self._deliver = deliver
        self._poll_timeout = float(poll_timeout_s)
        self._stop = threading.Event()
        self.error: Optional[BaseException] = None
        self.num_batches = 0
        self.num_fragments = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="sample_prefetcher"
        )
        self._thread.start()

    @property
    def manager(self) -> AsyncRequestsManager:
        return self._manager

    def _run(self) -> None:
        frag_buf: list = []
        frag_steps = 0
        try:
            while not self._stop.is_set():
                self._manager.submit_available()
                if not self._manager.in_flight():
                    # every worker dead or removed: spin politely so
                    # the driver can notice and recreate
                    self._stop.wait(self._poll_timeout)
                    continue
                ready = self._manager.get_ready(
                    timeout=self._poll_timeout
                )
                for _, results in ready.items():
                    for b in results:
                        frag_buf.append(b)
                        frag_steps += b.env_steps()
                        self.num_fragments += 1
                        if frag_steps < self._target:
                            continue
                        # target checked per fragment, not per harvest:
                        # batch composition stays deterministic for
                        # uniform fragments (ceil(target/frag) each)
                        # instead of depending on harvest timing
                        with tracing.start_span(
                            "prefetch:assemble",
                            fragments=len(frag_buf),
                            steps=frag_steps,
                        ):
                            batch = concat_samples(frag_buf)
                        frag_buf, frag_steps = [], 0
                        # blocks on feeder backpressure — that bound IS
                        # the prefetch depth / staleness bound
                        with tracing.start_span(
                            "prefetch:deliver"
                        ):
                            self._deliver(batch)
                        self.num_batches += 1
        except BaseException as e:  # surfaced via healthy()/error
            self.error = e

    def healthy(self) -> bool:
        return self.error is None and self._thread.is_alive()

    def take_dead_workers(self) -> List:
        return self._manager.take_dead_workers()

    def add_workers(self, workers: List) -> None:
        self._manager.add_workers(workers)

    def stats(self) -> dict:
        return {
            "num_train_batches": self.num_batches,
            "num_fragments": self.num_fragments,
            **self._manager.stats(),
        }

    def request_stop(self) -> None:
        """Signal the thread without joining. Call this BEFORE stopping
        the downstream feeder: a ``deliver`` blocked on feeder
        backpressure only unblocks when the feeder shuts down (its
        ``put`` raises), and the raise must find the stop flag set."""
        self._stop.set()

    def stop(self, join_timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout)
