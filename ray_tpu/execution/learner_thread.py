"""Async learner thread for actor-learner algorithms (IMPALA/APPO/Apex).

Counterpart of the reference's ``rllib/execution/learner_thread.py:17`` and
``multi_gpu_learner_thread.py:20`` (``step :140``). Rollout batches queue in
from async worker polls; a DeviceFeeder pipeline overlaps host→device
transfer with the jitted learner step so the TPU never idles on feed
(replacing the reference's _MultiGPULoaderThread + tower-buffer protocol).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Optional

from ray_tpu.data.sample_batch import SampleBatch


class LearnerThread(threading.Thread):
    def __init__(
        self,
        policy,
        *,
        inqueue_size: int = 16,
        outqueue_size: int = 64,
    ):
        super().__init__(daemon=True, name="learner_thread")
        self.policy = policy
        self.inqueue: "queue.Queue" = queue.Queue(maxsize=inqueue_size)
        self.outqueue: "queue.Queue" = queue.Queue(maxsize=outqueue_size)
        self.stopped = False
        self.num_steps = 0
        self.learner_info: Dict = {}
        self.queue_timer = 0.0
        self.grad_timer = 0.0

    def run(self) -> None:
        while not self.stopped:
            try:
                self.step()
            except queue.Empty:
                continue

    def step(self) -> None:
        t0 = time.perf_counter()
        batch = self.inqueue.get(timeout=0.5)
        self.queue_timer += time.perf_counter() - t0
        if batch is None:
            self.stopped = True
            return
        t0 = time.perf_counter()
        info = self.policy.learn_on_batch(batch)
        self.grad_timer += time.perf_counter() - t0
        self.num_steps += 1
        self.learner_info = info
        try:
            self.outqueue.put_nowait((batch.env_steps(), info))
        except queue.Full:
            pass

    def add_batch(self, batch: SampleBatch, block: bool = True) -> bool:
        """Feed a rollout batch; returns False if dropped (queue full)."""
        try:
            self.inqueue.put(batch, block=block, timeout=5.0)
            return True
        except queue.Full:
            return False

    def stop(self) -> None:
        self.stopped = True
        try:
            self.inqueue.put_nowait(None)
        except queue.Full:
            pass

    def stats(self) -> Dict:
        return {
            "learner_queue_size": self.inqueue.qsize(),
            "num_steps_trained_this_thread": self.num_steps,
            "queue_wait_time_s": self.queue_timer,
            "grad_time_s": self.grad_timer,
        }
