"""Async learner thread for actor-learner algorithms (IMPALA/APPO/Apex).

Counterpart of the reference's ``rllib/execution/learner_thread.py:17`` and
``multi_gpu_learner_thread.py:20`` (``step :140``). Rollout batches queue in
from async worker polls; a DeviceFeeder pipelines host→device transfer so
the copy of batch k+1 overlaps the jitted SGD step of batch k (the
reference's _MultiGPULoaderThread + tower-buffer protocol, collapsed to a
double-buffered ``jax.device_put`` thread). Policies without the two-phase
JaxPolicy learn API fall back to synchronous ``learn_on_batch``.

Two further overlaps matter on a tunneled/remote TPU backend, where a
single dispatch round trip can exceed the nest's compute:

- **Deferred stats.** For policies without host-side
  ``after_learn_on_batch`` hooks, ``learn_on_device_batch`` runs with
  ``defer_stats=True``: the thread never blocks on the stats fetch, so
  up to ``STATS_LAG`` SGD programs queue on-device and the dispatch
  latency amortizes across them. Stats materialize ``STATS_LAG`` steps
  later, when the program has already finished (a free fetch).
- **Learner-side weight publishing.** The thread pulls host weights
  every ``publish_weights_every`` steps right after a step completes and
  parks them in a versioned slot. The driver broadcasts the published
  blob to rollout workers without ever touching the device — the
  reference's weight lock + ``get_weights`` on the driver thread would
  serialize the driver against the learner's device queue here.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Dict, Optional, Tuple

import jax

from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing

# Transfers in flight ahead of the compute step. 2 = classic double
# buffering: one batch on device waiting, one being copied.
PIPELINE_DEPTH = 2
# SGD programs allowed in the device queue before the thread materializes
# the oldest stats (which bounds queue depth AND device memory: each
# queued program pins its input batch buffers).
STATS_LAG = 3


class LearnerThread(threading.Thread):
    def __init__(
        self,
        policy,
        *,
        inqueue_size: int = 16,
        outqueue_size: int = 64,
        publish_weights_every: int = 0,
    ):
        super().__init__(daemon=True, name="learner_thread")
        self.policy = policy
        self.inqueue: "queue.Queue" = queue.Queue(maxsize=inqueue_size)
        self.outqueue: "queue.Queue" = queue.Queue(maxsize=outqueue_size)
        self.stopped = False
        self.num_steps = 0
        self.learner_info: Dict = {}
        self.queue_timer = 0.0
        self.grad_timer = 0.0
        self.publish_timer = 0.0
        # Pipeline only policies using the JaxPolicy two-phase learn API
        # through the standard composition: a subclass that overrides
        # learn_on_batch itself has semantics the split would bypass.
        from ray_tpu.policy.jax_policy import JaxPolicy

        self._pipelined = isinstance(policy, JaxPolicy) and (
            type(policy).learn_on_batch is JaxPolicy.learn_on_batch
        )
        # Stats can be deferred (and dispatches pipelined on-device) only
        # when nothing host-side consumes them between steps.
        self._defer = self._pipelined and (
            type(policy).after_learn_on_batch
            is JaxPolicy.after_learn_on_batch
        )
        self._feeder = None
        self._in_flight = 0
        self._lazy: "collections.deque" = collections.deque()
        # superstep contract (docs/data_plane.md): fuse up to K queued
        # batches into ONE compiled K-update program — one dispatch +
        # one stats drain per superstep instead of per update. Only
        # for policies whose update body rides the generic scan, and
        # only on the two-phase deferred path (host stat hooks between
        # updates would observe nothing anyway).
        self._superstep_k = 1
        try:
            if self._defer and getattr(
                policy, "supports_superstep", False
            ):
                from ray_tpu.sharding.superstep import (
                    resolve_superstep,
                )

                self._superstep_k = resolve_superstep(
                    getattr(policy, "config", None) or {},
                    getattr(policy, "mesh", None),
                )
        except Exception:
            self._superstep_k = 1
        self._depth = max(PIPELINE_DEPTH, self._superstep_k)
        self._stack_fn = None
        # Weight publishing: (version, host_weights) swapped atomically.
        self._publish_every = int(publish_weights_every)
        self._weights_lock = threading.Lock()
        self._published: Optional[Tuple[int, Dict]] = None
        self._steps_since_publish = 0
        # resilience: a thread exception parks here (healthy() flips
        # False) instead of vanishing into a dead daemon thread; the
        # chaos harness can also crash the thread deterministically
        from ray_tpu.resilience import faults as faults_lib

        self.error: Optional[BaseException] = None
        self._fault_injector = faults_lib.from_config(
            getattr(policy, "config", None) or {}
        )

    def _get_feeder(self):
        # Lazy: build on the learner thread so jax initializes there.
        if self._feeder is None:
            from ray_tpu.execution.device_feed import DeviceFeeder

            self._feeder = DeviceFeeder(
                self.policy.batch_shardings,
                capacity=max(2, self._superstep_k),
            )
        return self._feeder

    def _trim_fixed(self, tree, bsize):
        """Fixed-row contract for superstep stacking: trim a prepared
        tree to the largest shard-divisible row count at or under the
        config's train-batch geometry, so every queued batch has the
        same shape and K of them stack into one scan feed. Frame-pool
        batches (per-batch pool sizes) demote the thread to per-update
        dispatch instead."""
        from ray_tpu.ops.framestack import FRAMES as _FRAMES

        if _FRAMES in tree:
            self._superstep_k = 1
            return tree, bsize
        policy = self.policy
        cfg = getattr(policy, "config", None) or {}
        target = int(cfg.get("train_batch_size", bsize))
        # IMPALA-family trees are (num_unrolls, T, ...): rows are
        # whole unrolls, not env steps
        frag_T = int(getattr(policy, "unroll_len", 0) or 0)
        rows_target = target // frag_T if frag_T else target
        div = max(1, getattr(policy, "n_shards", 1)) * max(
            1, getattr(policy, "_unroll_T", 1)
        )
        fixed = (rows_target // div) * div
        if fixed <= 0 or bsize < fixed:
            return tree, bsize
        if bsize == fixed:
            return tree, bsize
        T = max(1, getattr(policy, "_unroll_T", 1))
        tree = {
            c: (
                v[: fixed // T]
                if c.startswith("__chunk__")
                else v[:fixed]
            )
            for c, v in tree.items()
        }
        return tree, fixed

    # ray-tpu: thread=learner
    def run(self) -> None:
        try:
            while not self.stopped:
                try:
                    self.step()
                except queue.Empty:
                    # idle: everything queued on-device has finished by
                    # now — flush any remaining deferred stats
                    self._drain_lazy(all_of_them=True)
                    continue
            self._drain_lazy(all_of_them=True)
        except BaseException as e:  # surfaced via healthy()/error
            self.error = e
        finally:
            # The learner thread owns the feeder: stopping it here (not in
            # stop(), which runs on another thread) avoids racing an
            # in-progress _pump against the feeder's stopped flag.
            if self._feeder is not None:
                self._feeder.stop()

    # ray-tpu: thread=learner
    def _pump(self, block: bool) -> bool:
        """Move one host batch inqueue → feeder. Returns True if moved."""
        batch = self.inqueue.get(timeout=0.5) if block else (
            self.inqueue.get_nowait()
        )
        if batch is None:
            self.stopped = True
            return False
        tree, bsize = self.policy.prepare_batch(batch)
        if self._superstep_k > 1:
            tree, bsize = self._trim_fixed(tree, bsize)
        self._get_feeder().put(tree, (bsize, batch.env_steps()))
        self._in_flight += 1
        return True

    # the counted drain helper: deferred stats materialize here,
    # STATS_LAG programs behind the dispatch (a free fetch)
    # ray-tpu: thread=learner drain-ok
    def _drain_lazy(self, all_of_them: bool = False) -> None:
        """Materialize deferred stats older than STATS_LAG (their
        programs have finished; the fetch is a cheap copy-out)."""
        keep = 0 if all_of_them else STATS_LAG
        while len(self._lazy) > keep:
            env_steps, stats = self._lazy.popleft()
            stats = jax.device_get(stats)
            info = {k: float(v) for k, v in stats.items()}
            info["cur_lr"] = self.policy.coeff_values.get("lr")
            self.learner_info = info
            try:
                self.outqueue.put_nowait((env_steps, info))
            except queue.Full:
                pass

    # ray-tpu: thread=learner
    def _maybe_publish(self, steps: int = 1) -> None:
        if not self._publish_every:
            return
        self._steps_since_publish += steps
        if self._steps_since_publish < self._publish_every:
            return
        t0 = time.perf_counter()
        host_w = self.policy.get_weights()
        with self._weights_lock:
            ver = (self._published[0] if self._published else 0) + 1
            self._published = (ver, host_w)
        self._steps_since_publish = 0
        self.publish_timer += time.perf_counter() - t0

    def published_weights(self) -> Optional[Tuple[int, Dict]]:
        """Latest (version, host_weights) pulled by the learner thread,
        or None before the first publish. Never touches the device."""
        with self._weights_lock:
            return self._published

    def healthy(self) -> bool:
        """False once the thread died (injected crash or real bug);
        the parked exception is in :attr:`error`."""
        return self.error is None and self.is_alive()

    # ray-tpu: thread=learner hot-path
    def step(self) -> None:
        if self._fault_injector is not None:
            self._fault_injector.on_learner_thread_step()
        if not self._pipelined:
            return self._step_sync()
        t0 = time.perf_counter()
        t_wait0 = time.time()
        # Top up the transfer pipeline; block only when nothing is in
        # flight (otherwise learn on what we have).
        if self._in_flight == 0:
            if not self._pump(block=True):
                return
        while self._in_flight < self._depth:
            try:
                if not self._pump(block=False):
                    break
            except queue.Empty:
                break
        try:
            dev, (bsize, env_steps) = self._feeder.get()
        finally:
            # A failed transfer still consumed an in-flight slot.
            self._in_flight -= 1
        self.queue_timer += time.perf_counter() - t0
        tracing.record_span(
            "learner:queue_wait", t_wait0, time.time()
        )
        telemetry_metrics.set_queue_depth(
            "learner_in", self.inqueue.qsize()
        )
        t0 = time.perf_counter()
        if self._defer and self._superstep_k > 1:
            if self._step_superstep(dev, bsize, env_steps, t0):
                return
            # demoted mid-flight (frame pools / ragged shapes):
            # fall through to the per-update deferred path
        if self._defer:
            stats = self.policy.learn_on_device_batch(
                dev, bsize, defer_stats=True
            )
            self._lazy.append((env_steps, stats))
            self.grad_timer += time.perf_counter() - t0
            self.num_steps += 1
            self._maybe_publish()
            self._drain_lazy()
            return
        info = self.policy.learn_on_device_batch(dev, bsize)
        self.grad_timer += time.perf_counter() - t0
        self.num_steps += 1
        self.learner_info = info
        self._maybe_publish()
        try:
            self.outqueue.put_nowait((env_steps, info))
        except queue.Full:
            pass

    # ray-tpu: thread=learner hot-path
    def _step_superstep(self, dev, bsize, env_steps, t0) -> bool:
        """Fuse up to ``_superstep_k`` queued device batches into one
        compiled K-update dispatch (one stats drain for the chain).
        Returns False — without consuming anything — when the first
        batch can't ride the scan (frame pools: per-batch pool sizes),
        demoting the thread to per-update dispatch. A starved or
        ragged collection learns what it gathered per-update instead
        (deferred), so throughput degrades gracefully."""
        from ray_tpu.ops.framestack import FRAMES as _FRAMES

        if _FRAMES in dev:
            self._superstep_k = 1
            return False
        k_sup = self._superstep_k
        batches = [(dev, bsize, env_steps)]
        while len(batches) < k_sup:
            while self._in_flight < self._depth:
                try:
                    if not self._pump(block=False):
                        break
                except queue.Empty:
                    break
            if self._in_flight <= 0:
                break
            try:
                d2, (b2, e2) = self._feeder.get(timeout=10.0)
            except queue.Empty:
                break
            self._in_flight -= 1
            batches.append((d2, b2, e2))
        sizes = {b[1] for b in batches}
        if len(batches) == k_sup and len(sizes) == 1:
            if self._stack_fn is None:
                from ray_tpu import sharding as sharding_lib

                self._stack_fn = sharding_lib.build_stack_fn(
                    self.policy.mesh,
                    k_sup,
                    label=f"superstep_stack[{k_sup}]",
                )
            stacked = self._stack_fn(*[b[0] for b in batches])
            infos, _, skipped = self.policy.learn_superstep(
                k_sup, bsize, stacked=dict(stacked), k_max=k_sup
            )
            self.grad_timer += time.perf_counter() - t0
            self.num_steps += k_sup
            for (_, _, e_), info in zip(batches, infos):
                info["cur_lr"] = self.policy.coeff_values.get("lr")
                self.learner_info = info
                try:
                    self.outqueue.put_nowait((e_, info))
                except queue.Full:
                    pass
            for s in skipped:
                if s:
                    telemetry_metrics.inc_skipped_batches()
            self._maybe_publish(steps=k_sup)
            return True
        # starved/ragged collection: per-update deferred dispatch
        for d_, b_, e_ in batches:
            stats = self.policy.learn_on_device_batch(
                d_, b_, defer_stats=True
            )
            self._lazy.append((e_, stats))
            self.num_steps += 1
        self.grad_timer += time.perf_counter() - t0
        self._maybe_publish(steps=len(batches))
        self._drain_lazy()
        return True

    # ray-tpu: thread=learner
    def _step_sync(self) -> None:
        t0 = time.perf_counter()
        t_wait0 = time.time()
        batch = self.inqueue.get(timeout=0.5)
        self.queue_timer += time.perf_counter() - t0
        tracing.record_span(
            "learner:queue_wait", t_wait0, time.time()
        )
        if batch is None:
            self.stopped = True
            return
        t0 = time.perf_counter()
        info = self.policy.learn_on_batch(batch)
        self.grad_timer += time.perf_counter() - t0
        self.num_steps += 1
        self.learner_info = info
        self._maybe_publish()
        try:
            self.outqueue.put_nowait((batch.env_steps(), info))
        except queue.Full:
            pass

    def add_batch(self, batch: SampleBatch, block: bool = True) -> bool:
        """Feed a rollout batch; returns False if dropped (queue full)."""
        try:
            self.inqueue.put(batch, block=block, timeout=5.0)
            telemetry_metrics.set_queue_depth(
                "learner_in", self.inqueue.qsize()
            )
            return True
        except queue.Full:
            return False

    def stop(self, join_timeout: float = 30.0) -> None:
        self.stopped = True
        try:
            self.inqueue.put_nowait(None)
        except queue.Full:
            pass
        # Join before interpreter teardown: a daemon thread killed while
        # inside a jitted XLA call aborts the process ("FATAL: exception
        # not rethrown") instead of exiting cleanly.
        if self.is_alive() and threading.current_thread() is not self:
            self.join(timeout=join_timeout)

    def stats(self) -> Dict:
        telemetry_metrics.set_queue_depth(
            "learner_out", self.outqueue.qsize()
        )
        return {
            "learner_queue_size": self.inqueue.qsize(),
            "num_steps_trained_this_thread": self.num_steps,
            "queue_wait_time_s": self.queue_timer,
            "grad_time_s": self.grad_timer,
            "weight_publish_time_s": self.publish_timer,
        }
