"""Asynchronous host→device batch feeding (double buffering).

Counterpart of the reference's ``_MultiGPULoaderThread``
(``rllib/execution/multi_gpu_learner_thread.py:184``), which moved batches
into idle GPU tower buffers while the learner consumed others. Here a feeder
thread runs ``jax.device_put`` onto the learner mesh so the (often
bandwidth-bound) host→device transfer of batch k+1 overlaps the jitted SGD
compute of batch k.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Optional

import jax


class DeviceFeeder:
    def __init__(self, sharding=None, capacity: int = 2):
        self._sharding = sharding
        self._in: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._out: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="device_feeder"
        )
        self._thread.start()

    def _run(self):
        while True:
            item = self._in.get()
            if item is None:
                return
            host_batch, meta = item
            try:
                sharding = self._sharding
                if callable(sharding) and not hasattr(
                    sharding, "devices"
                ):
                    # per-batch sharding resolver (e.g.
                    # JaxPolicy.batch_shardings: frame pools ride
                    # replicated while row columns shard over data)
                    sharding = sharding(host_batch)
                if sharding is not None:
                    dev = jax.device_put(host_batch, sharding)
                else:
                    dev = jax.device_put(host_batch)
                jax.block_until_ready(dev)
                self._out.put((dev, meta))
            except Exception as e:  # surface to consumer, meta intact
                self._out.put((e, meta))

    def put(self, host_batch: Any, meta: Any = None) -> None:
        """Enqueue a host batch for transfer; ``meta`` rides along
        untransferred (batch size, env-step count, ...)."""
        if self._stopped:
            raise RuntimeError("feeder stopped")
        self._in.put((host_batch, meta))

    def get(self, timeout: Optional[float] = None):
        """Dequeue the next ``(device_batch, meta)`` pair (blocking).
        Raises the transfer error if that batch's device_put failed."""
        out = self._out.get(timeout=timeout)
        if isinstance(out[0], Exception):
            raise out[0]
        return out

    def qsize(self) -> int:
        return self._out.qsize()

    def stop(self) -> None:
        self._stopped = True
        self._in.put(None)
