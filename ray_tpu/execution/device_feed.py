"""Asynchronous host→device batch feeding (double buffering).

Counterpart of the reference's ``_MultiGPULoaderThread``
(``rllib/execution/multi_gpu_learner_thread.py:184``), which moved batches
into idle GPU tower buffers while the learner consumed others. Here a feeder
thread runs ``jax.device_put`` onto the learner mesh so the (often
bandwidth-bound) host→device transfer of batch k+1 overlaps the jitted SGD
compute of batch k.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

import jax

from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing


class DeviceFeeder:
    def __init__(self, sharding=None, capacity: int = 2):
        self._sharding = sharding
        self._in: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._out: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._stopped = False
        # guards the stopped flag vs. concurrent put(): without it a
        # producer racing stop() could block forever on a full inqueue
        # whose consumer thread has already exited
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="device_feeder"
        )
        self._thread.start()

    def _run(self):
        while True:
            # queue-wait vs transfer: two spans on this thread's lane,
            # so the chrome trace shows whether the feeder was starved
            # (waiting on the prefetcher) or busy moving bytes
            t_wait0 = time.time()
            item = self._in.get()
            tracing.record_span(
                "feeder:queue_wait", t_wait0, time.time()
            )
            if item is None:
                return
            host_batch, meta = item
            telemetry_metrics.set_queue_depth(
                "feeder_in", self._in.qsize()
            )
            try:
                import time as _time

                sharding = self._sharding
                if callable(sharding) and not hasattr(
                    sharding, "devices"
                ):
                    # per-batch sharding resolver (e.g.
                    # JaxPolicy.batch_shardings: frame pools ride
                    # replicated while row columns shard over data)
                    sharding = sharding(host_batch)
                from ray_tpu.sharding import tree_nbytes

                nbytes = tree_nbytes(host_batch)
                telemetry_metrics.add_h2d_bytes("feeder", nbytes)
                t0 = _time.perf_counter()
                # nbytes on the span: the timeline's transfer lane and
                # the report CLI read per-transfer payload off it
                with tracing.start_span(
                    "feeder:transfer", nbytes=nbytes
                ):
                    if sharding is not None:
                        dev = jax.device_put(host_batch, sharding)
                    else:
                        dev = jax.device_put(host_batch)
                    jax.block_until_ready(dev)
                # same series as the sync-path transfer timer in
                # JaxPolicy.learn_on_batch, so backend A/Bs compare
                # transfer cost regardless of which path fed the batch
                from ray_tpu.utils.metrics import timer_histogram

                timer_histogram(
                    "ray_tpu_learner_transfer_seconds"
                ).observe(_time.perf_counter() - t0)
                out = (dev, meta)
            except Exception as e:  # surface to consumer, meta intact
                out = (e, meta)
            # bounded put that stays responsive to stop(): a consumer
            # that vanished must not wedge this thread on a full
            # outqueue and with it the whole interpreter shutdown
            while True:
                with self._lock:
                    if self._stopped:
                        return
                try:
                    self._out.put(out, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def put(self, host_batch: Any, meta: Any = None) -> None:
        """Enqueue a host batch for transfer; ``meta`` rides along
        untransferred (batch size, env-step count, ...). Blocks while
        the pipeline is full (backpressure); raises once the feeder is
        stopped — including when stop() lands mid-block."""
        while True:
            # check-and-insert under one lock acquisition: once stop()
            # flips the flag (same lock), no item can slip in behind
            # the drain/sentinel — a producer blocked on backpressure
            # deterministically raises instead
            with self._lock:
                if self._stopped:
                    raise RuntimeError("feeder stopped")
                try:
                    self._in.put_nowait((host_batch, meta))
                    return
                except queue.Full:
                    pass
            time.sleep(0.01)

    def get(self, timeout: Optional[float] = None):
        """Dequeue the next ``(device_batch, meta)`` pair (blocking).
        Raises the transfer error if that batch's device_put failed."""
        out = self._out.get(timeout=timeout)
        telemetry_metrics.set_queue_depth(
            "feeder_out", self._out.qsize()
        )
        if isinstance(out[0], Exception):
            raise out[0]
        return out

    def qsize(self) -> int:
        return self._out.qsize()

    @staticmethod
    def _drain(q: "queue.Queue") -> None:
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                return

    def stop(self, join_timeout: float = 10.0) -> None:
        """Idempotent shutdown: reject new puts, deliver the sentinel
        even through a full inqueue, keep both queues draining so a
        blocked ``_run`` can reach it, and join the thread with a
        timeout (a daemon thread killed inside a jitted XLA call aborts
        the interpreter instead of exiting cleanly)."""
        with self._lock:
            self._stopped = True
        # make room for the sentinel: pending host batches are dead
        # weight once stopped
        while True:
            try:
                self._in.put_nowait(None)
                break
            except queue.Full:
                self._drain(self._in)
        deadline = time.monotonic() + join_timeout
        while self._thread.is_alive() and time.monotonic() < deadline:
            # _run may be blocked on a full outqueue between its stop
            # checks; keep it moving
            self._drain(self._out)
            self._thread.join(timeout=0.1)
        self._drain(self._in)
        self._drain(self._out)
