"""Training-step primitives.

Counterpart of the reference's ``rllib/execution/train_ops.py``
(``train_one_step :42``, ``multi_gpu_train_one_step :92``). The reference's
multi-GPU path — load_batch_into_buffer per device, threaded tower grads,
CPU averaging — is replaced by the JaxPolicy learner on the
``ray_tpu.sharding`` runtime: one device_put of the batch onto the mesh
(row-sharded columns, replicated params) and one ``sharded_jit``
multi-epoch SGD call, so both entry points below collapse to the same
code. Per-stage timers (transfer / compile / step) land in the policy's
``last_learn_timers`` and in the ``ray_tpu_learner_*_seconds``
histograms (utils/metrics.py); Algorithm.step copies them into
``results["info"]["timers"]``.
"""

from __future__ import annotations

from typing import Dict

from ray_tpu.data.sample_batch import (
    DEFAULT_POLICY_ID,
    MultiAgentBatch,
    SampleBatch,
)
from ray_tpu.utils.metrics import timer_histogram

NUM_ENV_STEPS_TRAINED = "num_env_steps_trained"
NUM_AGENT_STEPS_TRAINED = "num_agent_steps_trained"


def train_one_step(algorithm, train_batch) -> Dict:
    """reference train_ops.py:42.

    This is the driver-side learn choke point, so the resilience layer
    hooks in here (docs/resilience.md): the FaultInjector counts learn
    calls (NaN/Inf poisoning, injected crashes), and with
    ``config["nan_guard"]`` a non-finite batch is SKIPPED — counted in
    ``ray_tpu_skipped_batches_total`` and ``info/recovery`` — instead
    of being fed to the optimizer, where a single NaN would corrupt
    the params beyond repair."""
    import time as _time

    from ray_tpu.util import tracing

    injector = getattr(algorithm, "_fault_injector", None)
    if injector is not None:
        injector.on_learn(train_batch)
    if algorithm.config.get("nan_guard"):
        from ray_tpu.resilience.recovery import batch_is_finite

        if not batch_is_finite(train_batch):
            algorithm._counters["num_nan_batches_skipped"] += 1
            recovery = getattr(algorithm, "_recovery", None)
            if recovery is not None:
                recovery.note_skipped_batch()
            return {}

    local_worker = algorithm.workers.local_worker()
    t0 = _time.perf_counter()
    with tracing.start_span(
        "train:learn_on_batch",
        env_steps=int(train_batch.env_steps()),
    ):
        info = local_worker.learn_on_batch(train_batch)
    algorithm._timers["learn_on_batch_s"] = _time.perf_counter() - t0
    timer_histogram("ray_tpu_learner_total_seconds").observe(
        algorithm._timers["learn_on_batch_s"]
    )
    algorithm._counters[NUM_ENV_STEPS_TRAINED] += train_batch.env_steps()
    algorithm._counters[NUM_AGENT_STEPS_TRAINED] += (
        train_batch.agent_steps()
        if isinstance(train_batch, MultiAgentBatch)
        else train_batch.count
    )
    return info


# On TPU the multi-device path is identical — the mesh lives inside the
# policy (reference multi_gpu_train_one_step :92 needed a separate
# buffer-loading protocol; here sharding is a device_put detail).
multi_gpu_train_one_step = train_one_step
