"""Training-step primitives.

Counterpart of the reference's ``rllib/execution/train_ops.py``
(``train_one_step :42``, ``multi_gpu_train_one_step :92``). The reference's
multi-GPU path — load_batch_into_buffer per device, threaded tower grads,
CPU averaging — is replaced by the JaxPolicy learner on the
``ray_tpu.sharding`` runtime: one device_put of the batch onto the mesh
(row-sharded columns, replicated params) and one ``sharded_jit``
multi-epoch SGD call, so both entry points below collapse to the same
code. Per-stage timers (transfer / compile / step) land in the policy's
``last_learn_timers`` and in the ``ray_tpu_learner_*_seconds``
histograms (utils/metrics.py); Algorithm.step copies them into
``results["info"]["timers"]``.
"""

from __future__ import annotations

from typing import Dict

from ray_tpu.data.sample_batch import (
    DEFAULT_POLICY_ID,
    MultiAgentBatch,
    SampleBatch,
)
from ray_tpu.utils.metrics import timer_histogram

NUM_ENV_STEPS_TRAINED = "num_env_steps_trained"
NUM_AGENT_STEPS_TRAINED = "num_agent_steps_trained"


def train_one_step(algorithm, train_batch) -> Dict:
    """reference train_ops.py:42.

    This is the driver-side learn choke point, so the resilience layer
    hooks in here (docs/resilience.md): the FaultInjector counts learn
    calls (NaN/Inf poisoning, injected crashes), and with
    ``config["nan_guard"]`` a non-finite batch is SKIPPED — counted in
    ``ray_tpu_skipped_batches_total`` and ``info/recovery`` — instead
    of being fed to the optimizer, where a single NaN would corrupt
    the params beyond repair."""
    import time as _time

    from ray_tpu.util import tracing

    injector = getattr(algorithm, "_fault_injector", None)
    if injector is not None:
        injector.on_learn(train_batch)
    if algorithm.config.get("nan_guard"):
        from ray_tpu.resilience.recovery import batch_is_finite

        if not batch_is_finite(train_batch):
            algorithm._counters["num_nan_batches_skipped"] += 1
            recovery = getattr(algorithm, "_recovery", None)
            if recovery is not None:
                recovery.note_skipped_batch()
            return {}

    local_worker = algorithm.workers.local_worker()
    t0 = _time.perf_counter()
    with tracing.start_span(
        "train:learn_on_batch",
        env_steps=int(train_batch.env_steps()),
    ):
        info = local_worker.learn_on_batch(train_batch)
    algorithm._timers["learn_on_batch_s"] = _time.perf_counter() - t0
    timer_histogram("ray_tpu_learner_total_seconds").observe(
        algorithm._timers["learn_on_batch_s"]
    )
    algorithm._counters[NUM_ENV_STEPS_TRAINED] += train_batch.env_steps()
    algorithm._counters[NUM_AGENT_STEPS_TRAINED] += (
        train_batch.agent_steps()
        if isinstance(train_batch, MultiAgentBatch)
        else train_batch.count
    )
    return info


# On TPU the multi-device path is identical — the mesh lives inside the
# policy (reference multi_gpu_train_one_step :92 needed a separate
# buffer-loading protocol; here sharding is a device_put detail).
multi_gpu_train_one_step = train_one_step


def superstep_train_replay(
    algorithm,
    policy,
    buf,
    k: int,
    k_max: int,
    batch_size: int,
    *,
    prioritized: bool = False,
    beta: float = 0.4,
):
    """One fused superstep of ``k`` replay updates — the uniform
    K-updates-per-dispatch learner contract (docs/data_plane.md)
    shared by the whole DQN off-policy family.

    Index draws happen here, host-side, in the exact per-update
    generator call order (``draw_index_sets`` /
    ``draw_prioritized_sets``: k sequential draws, priorities frozen
    within the chain), then:

      - device-resident buffers hand their rings to the program
        (``superstep_feed``) — the scan gathers each update's rows in
        place, so only the ``(k, B)`` index matrix (plus PER weights)
        cross host→device;
      - host rings stack the k per-draw train trees into ONE
        ``(k, B, ...)`` H2D transfer.

    Prioritized buffers get the per-update ``|td|`` refresh as one
    stacked ``(k, B)`` D2H at superstep end, applied to the host sum
    tree in update order (bit-exact vs the per-update path given the
    same draws; nan-guard-skipped updates skip their refresh too).

    Returns the final update's stats dict, or None when this batch
    shape can't ride the scan (deduplicated frame pools) — the caller
    falls back to per-update chaining."""
    import jax
    import numpy as np

    from ray_tpu.execution.replay_buffer import DeviceReplayBuffer
    from ray_tpu.ops.framestack import FRAMES as _FRAMES

    device_mode = isinstance(buf, DeviceReplayBuffer) and not buf.spilled
    # a spilled device buffer delegates storage AND priority state to
    # its host ring — draw/update through that single source of truth
    src = (
        buf._host
        if isinstance(buf, DeviceReplayBuffer) and buf.spilled
        else buf
    )
    # device sum tree: the draw runs in-program and its (k_max, B)
    # index/weight matrices never exist host-side
    device_tree = (
        device_mode
        and prioritized
        and getattr(buf, "_dtree", None) is not None
    )
    refresh = prioritized and policy._td_error_device_fn() is not None
    pad = k_max - k
    if prioritized and device_tree:
        idx, weights = buf.draw_prioritized_sets_device(
            k, k_max, batch_size, beta
        )
    elif prioritized:
        idx, weights = src.draw_prioritized_sets(k, batch_size, beta)
    else:
        idx = src.draw_index_sets(k, batch_size)
        weights = None
    if pad and not device_tree:
        idx = np.concatenate(
            [idx, np.zeros((pad, batch_size), idx.dtype)]
        )
        if weights is not None:
            weights = np.concatenate(
                [weights, np.ones((pad, batch_size), np.float32)]
            )

    if device_mode:
        extra = (
            {"weights": weights.astype(np.float32)}
            if weights is not None
            else {}
        )
        feed = buf.superstep_feed(idx, extra)
        infos, pri, skipped = policy.learn_superstep(
            k,
            batch_size,
            rings=feed,
            k_max=k_max,
            refresh_priorities=refresh,
        )
    else:
        trees = []
        for i in range(k):
            b = src._make_batch(idx[i])
            if prioritized:
                # same columns the per-update PER sample carries
                b["weights"] = weights[i].astype(np.float32)
                b["batch_indexes"] = idx[i].astype(np.int64)
            tree, bsize = policy.prepare_batch(b)
            if bsize != batch_size or _FRAMES in tree:
                return None  # ragged/frame-pool batch: per-update path
            trees.append(tree)
        stacked = {
            c: np.stack([t[c] for t in trees]) for c in trees[0]
        }
        if pad:
            stacked = {
                c: np.concatenate([v, np.repeat(v[:1], pad, axis=0)])
                for c, v in stacked.items()
            }
        infos, pri, skipped = policy.learn_superstep(
            k,
            batch_size,
            stacked=stacked,
            k_max=k_max,
            refresh_priorities=refresh,
        )

    if prioritized and device_tree:
        if pri is not None:
            # ONE stacked device update, applied in update order with
            # the skipped slots masked — the host tree walk is gone;
            # what remains host-side is the alpha-power on the pulled
            # |td| (docs/data_plane.md "device sum tree")
            buf.refresh_priorities_stacked(
                idx[:k], pri, active=[not s for s in skipped]
            )
        else:
            for i in range(k):
                if skipped[i]:
                    continue
                buf.update_priorities(
                    idx[i],
                    np.full(
                        batch_size,
                        abs(infos[i].get("mean_td_error", 0.0)) + 1e-6,
                    ),
                )
    elif prioritized:
        # apply in update order: overlapping draws must resolve
        # exactly as the per-update path's interleaved writes would
        for i in range(k):
            if skipped[i]:
                continue
            if pri is not None:
                src.update_priorities(idx[i], pri[i] + 1e-6)
            else:
                # policies without per-sample errors: batch-mean
                # scalar fallback (mirrors DQN._single_update)
                src.update_priorities(
                    idx[i],
                    np.full(
                        batch_size,
                        abs(infos[i].get("mean_td_error", 0.0)) + 1e-6,
                    ),
                )

    n_skipped = sum(1 for s in skipped if s)
    if n_skipped and algorithm is not None:
        algorithm._counters["num_nan_batches_skipped"] += n_skipped
        recovery = getattr(algorithm, "_recovery", None)
        if recovery is not None:
            for _ in range(n_skipped):
                recovery.note_skipped_batch()
    return infos[-1] if infos else {}
