from ray_tpu.offline.dataset_reader import DatasetReader
from ray_tpu.offline.json_reader import JsonReader
from ray_tpu.offline.json_writer import JsonWriter
from ray_tpu.offline.off_policy_estimator import (
    ImportanceSampling,
    OffPolicyEstimator,
    WeightedImportanceSampling,
)

__all__ = [
    "DatasetReader",
    "JsonReader",
    "JsonWriter",
    "OffPolicyEstimator",
    "ImportanceSampling",
    "WeightedImportanceSampling",
]
