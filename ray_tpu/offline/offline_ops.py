"""Shared offline training-loop helpers (the reader→train-batch path
used by CQL/CRR; reference cql.py/crr.py keep SAC's loop and swap the
input source)."""

from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch, concat_samples


def setup_offline_reader(config: Dict):
    """Build the offline input for config["input"] (None when training
    from the sampler). Shared by MARWIL/BC/CQL/CRR setup. Accepts a
    JSON shard path/glob (JsonReader), a ``ray_tpu.data.Dataset`` of
    transition rows (DatasetReader — reference dataset_reader.py), or
    any object already exposing ``next() -> SampleBatch``."""
    inp = config.get("input_") or config.get("input")
    if inp is None or inp == "sampler":
        return None
    from ray_tpu.data.dataset import Dataset
    from ray_tpu.offline import DatasetReader, JsonReader

    if isinstance(inp, Dataset):
        return DatasetReader(inp)
    if isinstance(inp, str):
        return JsonReader(inp)
    if hasattr(inp, "next"):
        return inp
    raise ValueError(f"unsupported offline input: {type(inp)}")


def sample_offline_batch(
    reader,
    target: int,
    *,
    require_next_obs: bool = False,
    seed: int = 0,
) -> SampleBatch:
    """Draw >= target rows from the reader, then subsample exactly
    `target` rows uniformly (a fixed batch shape keeps the jitted learn
    program from recompiling)."""
    out, steps = [], 0
    while steps < target:
        b = reader.next()
        if require_next_obs and SampleBatch.NEXT_OBS not in b:
            raise ValueError(
                "offline data requires NEXT_OBS columns for TD learning"
            )
        out.append(b)
        steps += b.count
    batch = concat_samples(out)
    idx = np.random.default_rng(seed).permutation(batch.count)[:target]
    return SampleBatch(
        {k: np.asarray(v)[idx] for k, v in batch.items()}
    )


def offline_training_step(algo) -> Dict:
    """One offline train step: draw, learn, count (shared by CQL/CRR)."""
    from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID
    from ray_tpu.execution.train_ops import NUM_ENV_STEPS_TRAINED

    target = int(algo.config.get("train_batch_size", 256))
    batch = sample_offline_batch(
        algo._reader,
        target,
        require_next_obs=True,
        seed=algo._counters["offline_draws"],
    )
    algo._counters["offline_draws"] += 1
    info = algo.get_policy().learn_on_batch(batch)
    algo._counters[NUM_ENV_STEPS_TRAINED] += batch.count
    return {DEFAULT_POLICY_ID: info}
