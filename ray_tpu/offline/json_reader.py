"""JSON-lines SampleBatch reader (reference
``rllib/offline/json_reader.py``).

Reads shards written by :class:`JsonWriter` (exact numpy round trip) and
also tolerates reference-style plain-list columns. ``next()`` cycles
shards forever, shuffling line order per pass."""

from __future__ import annotations

import base64
import glob
import json
import os
import random
import zlib
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch, concat_samples


def _decode_col(v):
    if isinstance(v, dict) and v.get("__np__"):
        raw = zlib.decompress(base64.b64decode(v["data"]))
        return np.frombuffer(raw, np.dtype(v["dtype"])).reshape(
            v["shape"]
        ).copy()
    return np.asarray(v)


_META_KEYS = ("type", "count")


def json_to_batch(obj: Dict) -> SampleBatch:
    raw = obj.get("columns", obj)
    cols = {
        k: _decode_col(v)
        for k, v in raw.items()
        if k not in _META_KEYS  # reference-style lines keep metadata
        # next to the columns instead of under a "columns" key
    }
    return SampleBatch(cols)


class JsonReader:
    """reference json_reader.py JsonReader."""

    def __init__(self, inputs, ioctx=None, shuffle: bool = True):
        if isinstance(inputs, str):
            inputs = [inputs]
        files: List[str] = []
        for p in inputs:
            p = os.path.expanduser(p)
            if os.path.isdir(p):
                files += sorted(glob.glob(os.path.join(p, "*.json")))
            else:
                files += sorted(glob.glob(p))
        if not files:
            raise ValueError(f"No offline data files found in {inputs}")
        self.files = files
        self.shuffle = shuffle
        self._rng = random.Random(0)
        self._lines: List[str] = []
        self._cursor = 0
        self._load_pass()

    def _load_pass(self) -> None:
        lines = []
        for f in self.files:
            with open(f) as fh:
                lines += [ln for ln in fh if ln.strip()]
        if self.shuffle:
            self._rng.shuffle(lines)
        self._lines = lines
        self._cursor = 0

    def next(self) -> SampleBatch:
        """→ the next batch, cycling through all shards forever."""
        if self._cursor >= len(self._lines):
            self._load_pass()
        line = self._lines[self._cursor]
        self._cursor += 1
        return json_to_batch(json.loads(line))

    def read_all(self) -> SampleBatch:
        """Entire dataset as one concatenated batch (estimators,
        small-data BC)."""
        batches = [
            json_to_batch(json.loads(ln)) for ln in self._lines
        ]
        return concat_samples(batches)
