"""JSON-lines SampleBatch writer (reference
``rllib/offline/json_writer.py``).

One JSON object per line per batch. Numpy columns are stored exactly —
dtype + shape + zlib-compressed base64 payload — instead of the
reference's lossy float lists, so a write/read round trip is
bit-identical."""

from __future__ import annotations

import base64
import json
import os
import time
import zlib
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.data.sample_batch import MultiAgentBatch, SampleBatch


def _encode_col(v: Any):
    v = np.asarray(v)
    if v.dtype == object:
        return None  # unsupported column (e.g. infos dicts): dropped
    return {
        "__np__": True,
        "dtype": str(v.dtype),
        "shape": list(v.shape),
        "data": base64.b64encode(
            zlib.compress(np.ascontiguousarray(v).tobytes(), 3)
        ).decode("ascii"),
    }


def batch_to_json(batch: SampleBatch) -> Dict:
    cols = {}
    for k, v in batch.items():
        enc = _encode_col(v)
        if enc is not None:
            cols[k] = enc
    return {"type": "SampleBatch", "count": batch.count, "columns": cols}


class JsonWriter:
    """Writes batches to ``<path>/output-<ts>_<pid>.json``, rolling to a
    new shard at ``max_file_size`` bytes."""

    def __init__(
        self,
        path: str,
        max_file_size: int = 64 * 1024 * 1024,
        compress_columns=None,
    ):
        self.path = path
        self.max_file_size = max_file_size
        os.makedirs(path, exist_ok=True)
        self._f = None
        self._bytes = 0

    def _open(self):
        name = f"output-{time.strftime('%Y-%m-%d_%H-%M-%S')}_{os.getpid()}_{int(time.time_ns() % 1_000_000)}.json"
        self._f = open(os.path.join(self.path, name), "w")
        self._bytes = 0

    def write(self, batch) -> None:
        if isinstance(batch, MultiAgentBatch):
            for b in batch.policy_batches.values():
                self.write(b)
            return
        line = json.dumps(batch_to_json(batch))
        if self._f is None or self._bytes + len(line) > self.max_file_size:
            if self._f:
                self._f.close()
            self._open()
        self._f.write(line + "\n")
        self._f.flush()
        self._bytes += len(line) + 1

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None
