"""Off-policy evaluation estimators (reference
``rllib/offline/is_estimator.py`` / ``wis_estimator.py`` /
``off_policy_estimator.py``).

Given logged trajectories with behavior-policy action log-probs, score a
(new) target policy without running it in the env: per-step importance
ratios rho_t = pi_new(a|s)/pi_behavior(a|s), cumulated within each
episode.

- IS:  V = mean_episodes sum_t gamma^t * P_t * r_t with
  P_t = prod_{k<=t} rho_k.
- WIS: same numerator, but each P_t is normalized by its average over
  episodes at the same step index (weighted IS, lower variance)."""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch


class OffPolicyEstimator:
    def __init__(self, policy, gamma: float = 0.99):
        self.policy = policy
        self.gamma = gamma

    @classmethod
    def create_from_io_context(cls, ioctx) -> "OffPolicyEstimator":
        return cls(ioctx.policy, ioctx.config.get("gamma", 0.99))

    def _episodes(self, batch: SampleBatch) -> List[SampleBatch]:
        if SampleBatch.EPS_ID not in batch:
            return [batch]
        eps = np.asarray(batch[SampleBatch.EPS_ID])
        out = []
        for eid in np.unique(eps):
            idx = np.nonzero(eps == eid)[0]
            out.append(
                SampleBatch({k: np.asarray(v)[idx] for k, v in batch.items()})
            )
        return out

    def _ratios(self, episode: SampleBatch) -> np.ndarray:
        new_logp = self.policy.compute_log_likelihoods(
            episode[SampleBatch.ACTIONS], episode[SampleBatch.OBS]
        )
        old_logp = np.asarray(episode[SampleBatch.ACTION_LOGP])
        return np.exp(
            np.clip(new_logp - old_logp, -20.0, 20.0)
        )

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        raise NotImplementedError


class ImportanceSampling(OffPolicyEstimator):
    """reference is_estimator.py."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        v_behavior, v_target = [], []
        for ep in self._episodes(batch):
            rewards = np.asarray(ep[SampleBatch.REWARDS], np.float64)
            T = len(rewards)
            gammas = self.gamma ** np.arange(T)
            p = np.cumprod(self._ratios(ep))
            v_behavior.append(float((gammas * rewards).sum()))
            v_target.append(float((gammas * p * rewards).sum()))
        vb = float(np.mean(v_behavior))
        vt = float(np.mean(v_target))
        return {
            "v_behavior": vb,
            "v_target": vt,
            "v_gain": vt / vb if vb != 0 else np.nan,
        }


class WeightedImportanceSampling(OffPolicyEstimator):
    """reference wis_estimator.py."""

    def estimate(self, batch: SampleBatch) -> Dict[str, float]:
        episodes = self._episodes(batch)
        all_p: List[np.ndarray] = [
            np.cumprod(self._ratios(ep)) for ep in episodes
        ]
        max_t = max(len(p) for p in all_p)
        # per-step-index average of the cumulative ratios across
        # episodes (the WIS normalizer w_t)
        sums = np.zeros(max_t)
        counts = np.zeros(max_t)
        for p in all_p:
            sums[: len(p)] += p
            counts[: len(p)] += 1
        w = sums / np.maximum(counts, 1)
        v_behavior, v_target = [], []
        for ep, p in zip(episodes, all_p):
            rewards = np.asarray(ep[SampleBatch.REWARDS], np.float64)
            T = len(rewards)
            gammas = self.gamma ** np.arange(T)
            norm_p = p / np.maximum(w[:T], 1e-8)
            v_behavior.append(float((gammas * rewards).sum()))
            v_target.append(float((gammas * norm_p * rewards).sum()))
        vb = float(np.mean(v_behavior))
        vt = float(np.mean(v_target))
        return {
            "v_behavior": vb,
            "v_target": vt,
            "v_gain": vt / vb if vb != 0 else np.nan,
        }
