"""Dataset-backed offline input (reference
``rllib/offline/dataset_reader.py``): train from a
:class:`ray_tpu.data.Dataset` of transition rows instead of JSON shards,
so the Data layer's lazy map/filter/shuffle stages compose with offline
RL (the reference reads parquet/json through ``ray.data`` the same way).

Rows are dicts of per-transition column values (``obs``, ``actions``,
``rewards``, ...); ``next()`` yields fixed-size ``SampleBatch``es,
cycling and reshuffling the dataset every epoch."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.data.dataset import Dataset
from ray_tpu.data.sample_batch import SampleBatch


class DatasetReader:
    """reference dataset_reader.py DatasetReader."""

    def __init__(
        self,
        dataset: Dataset,
        ioctx=None,
        batch_size: int = 256,
        shuffle: bool = True,
        seed: Optional[int] = None,
    ):
        rows = dataset.take_all()
        if not rows:
            raise ValueError("empty dataset")
        if not isinstance(rows[0], dict):
            raise ValueError(
                "DatasetReader needs dict rows (column -> value per "
                f"transition), got {type(rows[0])}"
            )
        self._columns: Dict[str, np.ndarray] = {
            k: np.asarray([r[k] for r in rows]) for k in rows[0]
        }
        self._n = len(rows)
        self._batch_size = int(batch_size)
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._order = np.arange(self._n)
        self._pos = self._n  # trigger (re)shuffle on first next()

    def next(self) -> SampleBatch:
        if self._pos + self._batch_size > self._n:
            if self._shuffle:
                self._rng.shuffle(self._order)
            self._pos = 0
        sel = self._order[self._pos : self._pos + self._batch_size]
        self._pos += self._batch_size
        return SampleBatch(
            {k: v[sel] for k, v in self._columns.items()}
        )
