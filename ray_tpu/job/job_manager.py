"""Driver-side job manager: submitted entrypoints as supervised
subprocesses.

Counterpart of the reference's job submission stack
(``dashboard/modules/job/job_manager.py`` JobManager,
``job_head.py`` REST handlers): a job is a shell entrypoint run in its
own process with a runtime_env applied, its output captured to a
per-job log file, and its lifecycle tracked through the standard
status machine (PENDING → RUNNING → SUCCEEDED/FAILED/STOPPED).

TPU-first disposition: the reference runs each job through a
JobSupervisor actor so the job can land on any node of the cluster;
here the head host owns the chip, so jobs run as direct child
processes of the head — same lifecycle surface, no actor hop. The
job table persists through the pluggable store client
(``core/store_client.py``) when a state path is configured, so a
restarted head still lists finished jobs (reference: job table in the
GCS, recovered from Redis).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional


class JobStatus:
    """reference ``job/common.py JobStatus``."""

    PENDING = "PENDING"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"

    TERMINAL = (STOPPED, SUCCEEDED, FAILED)


@dataclass
class JobInfo:
    """reference ``job/common.py JobInfo``."""

    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    metadata: Dict[str, str] = field(default_factory=dict)
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    driver_exit_code: Optional[int] = None

    def to_dict(self) -> Dict:
        return asdict(self)


class JobManager:
    """Submit/supervise/stop jobs; one per head process."""

    def __init__(
        self,
        log_dir: Optional[str] = None,
        state_path: Optional[str] = None,
    ):
        import tempfile

        self.log_dir = log_dir or os.path.join(
            tempfile.gettempdir(), "ray_tpu_jobs"
        )
        os.makedirs(self.log_dir, exist_ok=True)
        self.lock = threading.Lock()
        self.jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._store = None
        state_path = state_path or os.environ.get("RAY_TPU_JOB_STATE")
        if state_path:
            from ray_tpu.core.store_client import make_store_client

            self._store = make_store_client(state_path)
            for blob in self._store.all("submissions").values():
                info = JobInfo(**json.loads(blob))
                if info.status not in JobStatus.TERMINAL:
                    # the supervising process died with the old head;
                    # the reference marks such jobs FAILED on recovery
                    info.status = JobStatus.FAILED
                    info.message = "head restarted while job was running"
                self.jobs[info.submission_id] = info

    # -- submission ------------------------------------------------------

    def submit_job(
        self,
        entrypoint: str,
        runtime_env: Optional[Dict] = None,
        submission_id: Optional[str] = None,
        metadata: Optional[Dict[str, str]] = None,
        packed_runtime_env: Optional[Dict] = None,
    ) -> str:
        """Start ``entrypoint`` as a supervised subprocess; returns the
        submission id (reference ``job_manager.py submit_job``).
        ``runtime_env`` is a spec with paths local to THIS host;
        ``packed_runtime_env`` is an already-packed env (archives
        inline) as shipped by a remote ``JobSubmissionClient``."""
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        with self.lock:
            if submission_id in self.jobs:
                raise ValueError(
                    f"job {submission_id!r} already submitted"
                )
            info = JobInfo(
                submission_id=submission_id,
                entrypoint=entrypoint,
                metadata=dict(metadata or {}),
            )
            self.jobs[submission_id] = info
        self._persist(info)
        env = dict(os.environ)
        env["RAY_TPU_JOB_ID"] = submission_id
        cwd = None
        packed = packed_runtime_env
        if runtime_env and packed is None:
            from ray_tpu.core.runtime_env import pack_runtime_env

            packed = pack_runtime_env(runtime_env)
        if packed:
            env.update(packed.get("env_vars") or {})
            cwd, extra_paths = self._materialize(packed)
            if extra_paths:
                env["PYTHONPATH"] = os.pathsep.join(
                    extra_paths
                    + [p for p in env.get("PYTHONPATH", "").split(
                        os.pathsep
                    ) if p]
                )
        log_path = self.log_path(submission_id)
        try:
            log_f = open(log_path, "wb")
            proc = subprocess.Popen(
                entrypoint,
                shell=True,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                env=env,
                cwd=cwd,
                start_new_session=True,  # signal the whole job group
            )
        except Exception as e:
            with self.lock:
                info.status = JobStatus.FAILED
                info.message = f"failed to start: {e!r}"
                info.end_time = time.time()
            self._persist(info)
            return submission_id
        with self.lock:
            info.status = JobStatus.RUNNING
            info.start_time = time.time()
            self._procs[submission_id] = proc
        self._persist(info)
        threading.Thread(
            target=self._supervise,
            args=(submission_id, proc, log_f),
            daemon=True,
            name=f"job_supervisor_{submission_id}",
        ).start()
        return submission_id

    def _materialize(self, packed: Dict):
        """Extract working_dir / py_modules archives for the job
        subprocess (same per-host content-addressed cache as
        task/actor runtime envs). working_dir becomes the job's cwd;
        py_modules land on its PYTHONPATH."""
        from ray_tpu.core.runtime_env import _cache_root, _extract

        cwd = None
        extra = []
        for archive in packed.get("archives") or []:
            dest = _extract(archive)
            if archive["kind"] == "working_dir":
                cwd = dest
                extra.insert(0, dest)
            else:
                # the module dir itself must be importable by name:
                # expose it via a parent dir holding a named symlink
                # (mirrors apply_runtime_env's py_module path)
                parent = os.path.join(
                    _cache_root(), f"mods_{archive['hash']}"
                )
                link = os.path.join(parent, archive["name"])
                os.makedirs(parent, exist_ok=True)
                if not os.path.exists(link):
                    try:
                        os.symlink(dest, link)
                    except OSError:
                        pass
                extra.append(parent)
        return cwd, extra

    def _supervise(self, submission_id: str, proc, log_f):
        rc = proc.wait()
        try:
            log_f.close()
        except Exception:
            pass
        with self.lock:
            info = self.jobs[submission_id]
            self._procs.pop(submission_id, None)
            if info.status == JobStatus.STOPPED:
                pass  # stop_job already wrote the terminal state
            elif rc == 0:
                info.status = JobStatus.SUCCEEDED
            else:
                info.status = JobStatus.FAILED
                info.message = f"entrypoint exited with code {rc}"
            info.driver_exit_code = rc
            info.end_time = time.time()
        self._persist(info)

    # -- queries ---------------------------------------------------------

    def get_job_status(self, submission_id: str) -> str:
        return self._get(submission_id).status

    def get_job_info(self, submission_id: str) -> JobInfo:
        return self._get(submission_id)

    def list_jobs(self) -> List[JobInfo]:
        with self.lock:
            return list(self.jobs.values())

    def log_path(self, submission_id: str) -> str:
        return os.path.join(self.log_dir, f"{submission_id}.log")

    def get_job_logs(self, submission_id: str) -> str:
        self._get(submission_id)  # raises on unknown id
        try:
            with open(self.log_path(submission_id), "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def _get(self, submission_id: str) -> JobInfo:
        with self.lock:
            if submission_id not in self.jobs:
                raise KeyError(f"no such job: {submission_id}")
            return self.jobs[submission_id]

    # -- control ---------------------------------------------------------

    def stop_job(self, submission_id: str, grace_s: float = 3.0) -> bool:
        """SIGTERM the job's process group, escalate to SIGKILL after
        ``grace_s`` (reference ``job_manager.py stop_job``'s
        SIGTERM→SIGKILL ladder). Returns False if already terminal."""
        with self.lock:
            info = self._get_locked(submission_id)
            proc = self._procs.get(submission_id)
            if info.status in JobStatus.TERMINAL or proc is None:
                return False
            info.status = JobStatus.STOPPED
            info.message = "stopped by user"
        self._persist(info)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return True
        deadline = time.time() + grace_s
        while time.time() < deadline:
            if proc.poll() is not None:
                return True
            time.sleep(0.05)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def _get_locked(self, submission_id: str) -> JobInfo:
        if submission_id not in self.jobs:
            raise KeyError(f"no such job: {submission_id}")
        return self.jobs[submission_id]

    def wait(
        self, submission_id: str, timeout: float = 60.0
    ) -> JobInfo:
        """Block until the job reaches a terminal status."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            info = self._get(submission_id)
            if info.status in JobStatus.TERMINAL:
                return info
            time.sleep(0.05)
        raise TimeoutError(
            f"job {submission_id} not terminal within {timeout}s"
        )

    def _persist(self, info: JobInfo) -> None:
        if self._store is None:
            return
        try:
            # "submissions", not "jobs": the runtime's driver-session
            # records own the "jobs" table in a shared state store
            self._store.put(
                "submissions",
                info.submission_id,
                json.dumps(info.to_dict()).encode(),
            )
        except Exception:
            # a broken/closed state store must not take down job
            # supervision or stop_job — persistence is best-effort
            pass

    def shutdown(self) -> None:
        with self.lock:
            procs = list(self._procs.items())
        for sid, _ in procs:
            try:
                self.stop_job(sid)
            except Exception:
                pass
        if self._store is not None:
            self._store.close()
