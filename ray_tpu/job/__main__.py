"""``python -m ray_tpu.job`` — job submission CLI.

Counterpart of the reference's ``ray job submit/status/logs/list/stop``
(``dashboard/modules/job/cli.py``), talking to a head's dashboard URL.

    python -m ray_tpu.job submit --address http://head:8265 \
        --working-dir ./proj -- python train_script.py
    python -m ray_tpu.job status --address ... <submission_id>
    python -m ray_tpu.job logs --address ... <submission_id>
    python -m ray_tpu.job list --address ...
    python -m ray_tpu.job stop --address ... <submission_id>
"""

from __future__ import annotations

import argparse
import json
import shlex
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # --address is accepted anywhere before the "--" entrypoint
    # separator (argparse subparser defaults clobber a value given
    # before the subcommand, so handle it by hand)
    address = "http://127.0.0.1:8265"
    limit = argv.index("--") if "--" in argv else len(argv)
    if "--address" in argv[:limit]:
        i = argv.index("--address")
        if i + 1 >= limit:
            print("error: --address needs a value", file=sys.stderr)
            return 2
        address = argv[i + 1]
        del argv[i : i + 2]
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.job", description="ray_tpu job CLI"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_submit = sub.add_parser("submit")
    p_submit.add_argument("--working-dir", default=None)
    p_submit.add_argument(
        "--runtime-env-json", default=None,
        help='full runtime_env as json, e.g. \'{"env_vars": {...}}\'',
    )
    p_submit.add_argument("--submission-id", default=None)
    p_submit.add_argument(
        "--no-wait", action="store_true",
        help="return immediately instead of tailing to completion",
    )
    p_submit.add_argument("entrypoint", nargs=argparse.REMAINDER)

    for name in ("status", "logs", "stop"):
        p = sub.add_parser(name)
        p.add_argument("submission_id")
    sub.add_parser("list")

    args = parser.parse_args(argv)
    from ray_tpu.job.client import JobSubmissionClient

    client = JobSubmissionClient(address)

    if args.cmd == "submit":
        entry = args.entrypoint
        if entry and entry[0] == "--":
            entry = entry[1:]
        if not entry:
            parser.error("no entrypoint given (after --)")
        runtime_env = (
            json.loads(args.runtime_env_json)
            if args.runtime_env_json
            else {}
        )
        if args.working_dir:
            runtime_env["working_dir"] = args.working_dir
        sid = client.submit_job(
            shlex.join(entry),
            runtime_env=runtime_env or None,
            submission_id=args.submission_id,
        )
        print(f"submitted: {sid}")
        if args.no_wait:
            return 0
        info = client.wait_until_terminal(sid)
        sys.stdout.write(client.get_job_logs(sid))
        print(f"status: {info['status']}")
        return 0 if info["status"] == "SUCCEEDED" else 1
    if args.cmd == "status":
        print(json.dumps(client.get_job_info(args.submission_id)))
        return 0
    if args.cmd == "logs":
        sys.stdout.write(client.get_job_logs(args.submission_id))
        return 0
    if args.cmd == "stop":
        stopped = client.stop_job(args.submission_id)
        print(f"stopped: {stopped}")
        return 0
    if args.cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2))
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
