from ray_tpu.job.job_manager import JobInfo, JobManager, JobStatus
from ray_tpu.job.client import JobSubmissionClient

__all__ = [
    "JobInfo",
    "JobManager",
    "JobStatus",
    "JobSubmissionClient",
]
