"""HTTP client for the job-submission REST surface.

Counterpart of the reference's ``ray.job_submission.JobSubmissionClient``
(``dashboard/modules/job/sdk.py``): talks to a head's dashboard
(``DashboardLite``) over plain HTTP with stdlib urllib — jobs can be
submitted, listed, tailed, and stopped from any machine that can reach
the dashboard port.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional


class JobSubmissionClient:
    def __init__(self, address: str):
        """``address`` is the dashboard URL, e.g.
        ``http://127.0.0.1:8265`` (scheme optional)."""
        if "://" not in address:
            address = f"http://{address}"
        self.address = address.rstrip("/")

    def _request(
        self, path: str, payload: Optional[Dict] = None
    ) -> Dict:
        url = f"{self.address}{path}"
        data = (
            json.dumps(payload).encode() if payload is not None else None
        )
        req = urllib.request.Request(
            url,
            data=data,
            method="POST" if data is not None else "GET",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            if e.code == 404:
                raise KeyError(detail) from None
            raise RuntimeError(
                f"job API {path} failed ({e.code}): {detail}"
            ) from None

    def submit_job(
        self,
        entrypoint: str,
        runtime_env: Optional[Dict] = None,
        submission_id: Optional[str] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        payload: Dict = {"entrypoint": entrypoint}
        if runtime_env:
            # pack CLIENT-side so working_dir/py_modules paths resolve
            # on this machine, then ship the content-addressed archives
            # in the request (the reference uploads working_dir
            # packages to the GCS the same way, sdk.py upload_*)
            import base64

            from ray_tpu.core.runtime_env import pack_runtime_env

            packed = pack_runtime_env(runtime_env) or {}
            wire = {
                k: v for k, v in packed.items() if k != "archives"
            }
            if packed.get("archives"):
                wire["archives"] = [
                    {
                        **a,
                        "data": base64.b64encode(a["data"]).decode(),
                    }
                    for a in packed["archives"]
                ]
            payload["packed_runtime_env"] = wire
        if submission_id:
            payload["submission_id"] = submission_id
        if metadata:
            payload["metadata"] = metadata
        return self._request("/api/jobs", payload)["submission_id"]

    def list_jobs(self) -> List[Dict]:
        return self._request("/api/jobs")

    def get_job_info(self, submission_id: str) -> Dict:
        return self._request(f"/api/jobs/{submission_id}")

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def get_job_logs(self, submission_id: str) -> str:
        return self._request(f"/api/jobs/{submission_id}/logs")["logs"]

    def stop_job(self, submission_id: str) -> bool:
        return self._request(
            f"/api/jobs/{submission_id}/stop", payload={}
        )["stopped"]

    def wait_until_terminal(
        self, submission_id: str, timeout: float = 300.0
    ) -> Dict:
        import time

        from ray_tpu.job.job_manager import JobStatus

        deadline = time.time() + timeout
        while time.time() < deadline:
            info = self.get_job_info(submission_id)
            if info["status"] in JobStatus.TERMINAL:
                return info
            time.sleep(0.2)
        raise TimeoutError(
            f"job {submission_id} not terminal within {timeout}s"
        )
