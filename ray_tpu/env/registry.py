"""Env registry + creation (reference ``ray/tune/registry.py`` register_env
+ RolloutWorker env creation)."""

from __future__ import annotations

from typing import Any, Callable, Dict

from ray_tpu.env.env_context import EnvContext

_env_registry: Dict[str, Callable] = {}


def register_env(name: str, creator: Callable[[EnvContext], Any]) -> None:
    _env_registry[name] = creator


def get_env_creator(env_spec) -> Callable[[EnvContext], Any]:
    """env_spec: registered name | gymnasium id | callable | env class."""
    if callable(env_spec) and not isinstance(env_spec, str):
        if isinstance(env_spec, type):
            return lambda cfg: env_spec(cfg)
        return env_spec
    if env_spec in _env_registry:
        return _env_registry[env_spec]
    if isinstance(env_spec, str) and (
        env_spec.startswith(
            ("PongLite", "Synthetic", "CartPoleJax", "GridRoomsJax")
        )
    ):
        # in-repo envs register on import; pull them in so yaml/CLI
        # runs can name them without a registration preamble
        # (reference tuned-example UX)
        import ray_tpu.env.jax_control  # noqa: F401
        import ray_tpu.env.jax_pong  # noqa: F401
        import ray_tpu.env.pong_lite  # noqa: F401
        import ray_tpu.env.synthetic_env  # noqa: F401

        if env_spec in _env_registry:
            return _env_registry[env_spec]
        # recognized in-repo prefix but no such registration: fail
        # fast at config time with the real names, instead of a
        # confusing gymnasium NameNotFound inside every worker
        raise ValueError(
            f"unknown in-repo env {env_spec!r}; registered: "
            f"{sorted(n for n in _env_registry)}"
        )

    def gym_creator(cfg: EnvContext):
        import gymnasium as gym

        return gym.make(env_spec, **{
            k: v for k, v in dict(cfg).items() if k != "render_mode"
        })

    return gym_creator
