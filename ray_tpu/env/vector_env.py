"""Vectorized environment over N sub-envs.

Counterpart of the reference's ``rllib/env/vector_env.py:23``
(``vectorize_gym_envs :42``). Steps sub-envs serially in-process (they live
on CPU actors); auto-resets on episode end and surfaces the terminal
observation so the sampler can bootstrap correctly.

**Terminal-observation contract** (audited in tests/test_jax_env.py —
the device rollout lane must match it exactly): ``vector_step`` never
auto-resets; at a ``terminated | truncated`` step it returns the
env's FINAL observation, which the sampler records as that row's
NEXT_OBS (the GAE bootstrap reads it: 0 across ``terminated``,
V(final obs) across ``truncated``). The sampler then calls
``reset_at(index)`` and the RESET observation becomes the successor
row's OBS. The JAX-native counterpart pins the same contract in
``env/jax_env.py`` (its adapter implements THIS protocol over the
pure-function API).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np


class VectorEnv:
    def __init__(self, observation_space, action_space, num_envs: int):
        self.observation_space = observation_space
        self.action_space = action_space
        self.num_envs = num_envs

    @staticmethod
    def vectorize_gym_envs(
        make_env: Callable[[int], Any],
        num_envs: int,
        seed: Optional[int] = None,
    ) -> "_VectorizedGymEnv":
        envs = [make_env(i) for i in range(num_envs)]
        return _VectorizedGymEnv(envs, seed=seed)

    def vector_reset(self) -> Tuple[List[Any], List[dict]]:
        raise NotImplementedError

    def reset_at(self, index: int) -> Tuple[Any, dict]:
        raise NotImplementedError

    def vector_step(self, actions):
        """→ (obs, rewards, terminateds, truncateds, infos)."""
        raise NotImplementedError

    def get_sub_environments(self) -> List[Any]:
        return []


class _VectorizedGymEnv(VectorEnv):
    def __init__(self, envs: List[Any], seed: Optional[int] = None):
        super().__init__(
            envs[0].observation_space, envs[0].action_space, len(envs)
        )
        self.envs = envs
        self._seed = seed

    def vector_reset(self):
        obs, infos = [], []
        for i, e in enumerate(self.envs):
            seed = None if self._seed is None else self._seed + i
            o, info = e.reset(seed=seed)
            obs.append(o)
            infos.append(info)
        return obs, infos

    def reset_at(self, index: int):
        return self.envs[index].reset()

    def vector_step(self, actions):
        obs, rewards, terms, truncs, infos = [], [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, info = e.step(a)
            obs.append(o)
            rewards.append(float(r))
            terms.append(bool(term))
            truncs.append(bool(trunc))
            infos.append(info)
        return obs, rewards, terms, truncs, infos

    def get_sub_environments(self):
        return self.envs
