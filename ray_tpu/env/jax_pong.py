"""PongLiteJax: the jittable port of :mod:`ray_tpu.env.pong_lite` for
the device rollout lane (docs/pipeline.md).

Same observation/compute shape as the numpy PongLite — 84x84 uint8
grayscale frames rendered from (ball, paddle) state, Discrete(3)
actions, +1 paddle contact / -1 miss, ``rallies`` rallies per episode,
truncation at ``max_steps`` — expressed as pure JAX functions over an
explicit state dict so act → step → postprocess lowers into one
compiled program on the learner mesh. Dynamics are a faithful port
(same constants, same update order); the serve randomness comes from
the state's carried PRNG key (jax threefry) instead of the numpy
generator, so episode CONTENT differs from the numpy env while the
task is identical. Parity between the two LANES (device engine vs the
host adapter) is exact because both run these same functions.

Frames render flat (84, 84, 1) — the device lane trains straight from
single frames (no host-side FrameStack wrapper; a stacking variant
belongs to the wrapper layer, not the env).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.env.jax_env import ArraySpec, JaxVectorEnv

_SIZE = 84
_PADDLE_H = 12
_PADDLE_W = 2
_BALL = 2
_SPEED = 2.2


class PongLiteJax(JaxVectorEnv):
    obs_spec = ArraySpec((_SIZE, _SIZE, 1), np.uint8)
    action_spec = ArraySpec((), np.int32, num_values=3)

    def __init__(self, config: Optional[Dict] = None):
        super().__init__(config)
        cfg = self.config
        self.rallies_per_episode = int(cfg.get("rallies", 21))
        self.max_steps = int(cfg.get("max_steps", 1000))
        self.paddle_speed = float(cfg.get("paddle_speed", 3.0))

    # -- helpers ---------------------------------------------------------

    @staticmethod
    def _serve(key):
        """(by, vx, vy) of a fresh serve, drawn from ``key`` (the jax
        counterpart of PongLite._serve; bx is the fixed serve line)."""
        import jax
        import jax.numpy as jnp

        k1, k2 = jax.random.split(key)
        by = jax.random.uniform(
            k1, (), minval=float(_BALL), maxval=float(_SIZE - _BALL)
        )
        angle = jax.random.uniform(k2, (), minval=-0.7, maxval=0.7)
        return by, _SPEED * jnp.cos(angle), _SPEED * jnp.sin(angle)

    @staticmethod
    def _render(py, bx, by):
        import jax.numpy as jnp

        rows = jnp.arange(_SIZE)
        cols = jnp.arange(_SIZE)
        byi = by.astype(jnp.int32)
        bxi = bx.astype(jnp.int32)
        pyi = py.astype(jnp.int32)
        ball = (
            (rows[:, None] >= jnp.maximum(0, byi - _BALL))
            & (rows[:, None] < byi + _BALL)
            & (cols[None, :] >= jnp.maximum(0, bxi - _BALL))
            & (cols[None, :] < bxi + _BALL)
        )
        paddle = (
            (rows[:, None] >= jnp.maximum(0, pyi - _PADDLE_H // 2))
            & (rows[:, None] < pyi + _PADDLE_H // 2)
            & (cols[None, :] >= _SIZE - _PADDLE_W - 1)
            & (cols[None, :] < _SIZE - 1)
        )
        frame = jnp.where(ball, 255, jnp.where(paddle, 180, 0))
        return frame.astype(jnp.uint8)[:, :, None]

    # -- JaxVectorEnv ----------------------------------------------------

    def init(self, key):
        import jax.numpy as jnp

        return {
            "key": key,
            "py": jnp.float32(0.0),
            "bx": jnp.float32(0.0),
            "by": jnp.float32(0.0),
            "vx": jnp.float32(0.0),
            "vy": jnp.float32(0.0),
            "rallies": jnp.int32(0),
            "steps": jnp.int32(0),
        }

    def reset(self, state):
        import jax
        import jax.numpy as jnp

        key, sk = jax.random.split(state["key"])
        by, vx, vy = self._serve(sk)
        state = {
            "key": key,
            "py": jnp.float32(_SIZE / 2.0),
            "bx": jnp.float32(_SIZE * 0.3),
            "by": by,
            "vx": vx,
            "vy": vy,
            "rallies": jnp.int32(0),
            "steps": jnp.int32(0),
        }
        return state, self._render(
            state["py"], state["bx"], state["by"]
        )

    def step(self, state, action):
        import jax
        import jax.numpy as jnp

        speed = jnp.float32(self.paddle_speed)
        py = state["py"]
        py = jnp.where(
            action == 1, py - speed, jnp.where(action == 2, py + speed, py)
        )
        py = jnp.clip(
            py, _PADDLE_H / 2.0, float(_SIZE - _PADDLE_H / 2)
        )

        bx = state["bx"] + state["vx"]
        by = state["by"] + state["vy"]
        vx, vy = state["vx"], state["vy"]
        # top/bottom and left-wall bounces (same order as the numpy env)
        wall = (by <= _BALL) | (by >= _SIZE - _BALL)
        vy = jnp.where(wall, -vy, vy)
        by = jnp.clip(by, float(_BALL), float(_SIZE - _BALL))
        left = bx <= _BALL
        vx = jnp.where(left, jnp.abs(vx), vx)
        bx = jnp.where(left, jnp.float32(_BALL), bx)

        paddle_x = _SIZE - _PADDLE_W - 1
        at_paddle = bx >= paddle_x - _BALL
        hit = at_paddle & (
            jnp.abs(by - py) <= _PADDLE_H / 2.0 + _BALL
        )
        reward = jnp.where(
            at_paddle,
            jnp.where(hit, jnp.float32(1.0), jnp.float32(-1.0)),
            jnp.float32(0.0),
        )
        # contact: reflect + spin + pin to the contact line
        vx = jnp.where(hit, -jnp.abs(vx), vx)
        vy = jnp.where(
            hit, vy + 0.5 * (by - py) / (_PADDLE_H / 2.0), vy
        )
        bx = jnp.where(hit, jnp.float32(paddle_x - _BALL), bx)

        rallies = state["rallies"] + at_paddle.astype(jnp.int32)
        # serve a new rally (hit or miss) while the episode continues;
        # the draw comes from the carried key, advanced every step so
        # both lanes consume the identical stream
        key, sk = jax.random.split(state["key"])
        s_by, s_vx, s_vy = self._serve(sk)
        serve = at_paddle & (rallies < self.rallies_per_episode)
        bx = jnp.where(serve, jnp.float32(_SIZE * 0.3), bx)
        by = jnp.where(serve, s_by, by)
        vx = jnp.where(serve, s_vx, vx)
        vy = jnp.where(serve, s_vy, vy)

        steps = state["steps"] + 1
        terminated = rallies >= self.rallies_per_episode
        truncated = steps >= self.max_steps
        state = {
            "key": key,
            "py": py,
            "bx": bx,
            "by": by,
            "vx": vx,
            "vy": vy,
            "rallies": rallies,
            "steps": steps,
        }
        return (
            state,
            self._render(py, bx, by),
            reward,
            terminated,
            truncated,
        )


from ray_tpu.env.registry import register_env  # noqa: E402

register_env("PongLiteJax-v0", lambda cfg: PongLiteJax(cfg))
