"""Multi-agent environment interface.

Counterpart of the reference's ``rllib/env/multi_agent_env.py:29``: dict-in /
dict-out stepping keyed by agent id, with the special ``__all__`` key in the
terminated/truncated dicts.
"""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple


class MultiAgentEnv:
    def __init__(self):
        self._agent_ids: Set = set()
        if not hasattr(self, "observation_space"):
            self.observation_space = None
        if not hasattr(self, "action_space"):
            self.action_space = None

    def reset(
        self, *, seed: Optional[int] = None, options: Optional[dict] = None
    ) -> Tuple[Dict, Dict]:
        """→ (obs_dict, info_dict) for the agents acting first."""
        raise NotImplementedError

    def step(self, action_dict: Dict):
        """→ (obs, rewards, terminateds, truncateds, infos) dicts. The
        terminateds/truncateds dicts carry '__all__'."""
        raise NotImplementedError

    def get_agent_ids(self) -> Set:
        return self._agent_ids

    def observation_space_sample(self):
        return {
            aid: self.observation_space.sample() for aid in self._agent_ids
        }

    def action_space_sample(self):
        return {aid: self.action_space.sample() for aid in self._agent_ids}


def make_multi_agent(env_name_or_creator):
    """Turn a single-agent env into N independent-agent copies
    (reference multi_agent_env.py make_multi_agent)."""
    import gymnasium as gym

    class IndependentMultiEnv(MultiAgentEnv):
        def __init__(self, config=None):
            super().__init__()
            config = config or {}
            num = config.get("num_agents", 2)
            if callable(env_name_or_creator):
                self.envs = [env_name_or_creator(config) for _ in range(num)]
            else:
                self.envs = [gym.make(env_name_or_creator) for _ in range(num)]
            self._agent_ids = set(range(num))
            self.observation_space = self.envs[0].observation_space
            self.action_space = self.envs[0].action_space
            self.terminateds = set()
            self.truncateds = set()

        def reset(self, *, seed=None, options=None):
            self.terminateds = set()
            self.truncateds = set()
            obs, infos = {}, {}
            for i, e in enumerate(self.envs):
                obs[i], infos[i] = e.reset(
                    seed=None if seed is None else seed + i
                )
            return obs, infos

        def step(self, action_dict):
            obs, rew, term, trunc, info = {}, {}, {}, {}, {}
            for i, action in action_dict.items():
                obs[i], rew[i], term[i], trunc[i], info[i] = self.envs[
                    i
                ].step(action)
                if term[i]:
                    self.terminateds.add(i)
                if trunc[i]:
                    self.truncateds.add(i)
            term["__all__"] = len(self.terminateds) == len(self.envs)
            trunc["__all__"] = len(self.truncateds) == len(self.envs)
            return obs, rew, term, trunc, info

    return IndependentMultiEnv
