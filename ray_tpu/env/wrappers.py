"""Env wrappers: Atari deepmind-style preprocessing + frame stacking.

Counterpart of the reference's ``rllib/env/wrappers/atari_wrappers.py``
(wrap_deepmind). ALE may not be installed in every image; the wrappers that
don't need it (FrameStack, NormalizedImageEnv, TimeLimit) work for any env
with image observations.
"""

from __future__ import annotations

from collections import deque

import numpy as np

try:
    import gymnasium as gym
    from gymnasium import spaces
except ImportError:  # pragma: no cover
    gym = None


def is_atari(env) -> bool:
    return (
        hasattr(env, "unwrapped")
        and type(env.unwrapped).__module__.startswith("ale_py")
    )


class FrameStack(gym.Wrapper):
    """Stack the last k frames along the channel axis
    (reference atari_wrappers.py FrameStack)."""

    def __init__(self, env, k: int = 4):
        super().__init__(env)
        self.k = k
        self.frames = deque([], maxlen=k)
        shp = env.observation_space.shape
        self.observation_space = spaces.Box(
            low=0,
            high=255,
            shape=(shp[0], shp[1], shp[2] * k),
            dtype=env.observation_space.dtype,
        )

    def reset(self, **kwargs):
        ob, info = self.env.reset(**kwargs)
        for _ in range(self.k):
            self.frames.append(ob)
        return self._get_ob(), info

    def step(self, action):
        ob, reward, term, trunc, info = self.env.step(action)
        self.frames.append(ob)
        return self._get_ob(), reward, term, trunc, info

    def _get_ob(self):
        return np.concatenate(list(self.frames), axis=2)


class MaxAndSkipEnv(gym.Wrapper):
    """Repeat action k times, max over last two frames
    (reference MaxAndSkipEnv)."""

    def __init__(self, env, skip: int = 4):
        super().__init__(env)
        self._obs_buffer = np.zeros(
            (2,) + env.observation_space.shape,
            dtype=env.observation_space.dtype,
        )
        self._skip = skip

    def step(self, action):
        total_reward = 0.0
        term = trunc = False
        info = {}
        for i in range(self._skip):
            obs, reward, term, trunc, info = self.env.step(action)
            if i == self._skip - 2:
                self._obs_buffer[0] = obs
            if i == self._skip - 1:
                self._obs_buffer[1] = obs
            total_reward += float(reward)
            if term or trunc:
                break
        return (
            self._obs_buffer.max(axis=0),
            total_reward,
            term,
            trunc,
            info,
        )

    def reset(self, **kwargs):
        return self.env.reset(**kwargs)


class ClipRewardEnv(gym.RewardWrapper):
    def reward(self, reward):
        return float(np.sign(reward))


class WarpFrame(gym.ObservationWrapper):
    """84x84 grayscale via numpy area pooling (reference WarpFrame uses
    cv2; box-mean downsampling avoids the cv2 dependency)."""

    def __init__(self, env, dim: int = 84):
        super().__init__(env)
        self.dim = dim
        self.observation_space = spaces.Box(
            low=0, high=255, shape=(dim, dim, 1), dtype=np.uint8
        )

    def observation(self, frame):
        if frame.ndim == 3 and frame.shape[2] == 3:
            frame = (
                0.299 * frame[..., 0]
                + 0.587 * frame[..., 1]
                + 0.114 * frame[..., 2]
            )
        h, w = frame.shape[:2]
        # crop to a multiple of dim, then area-average pool
        fh, fw = h // self.dim, w // self.dim
        if fh >= 1 and fw >= 1:
            frame = frame[: fh * self.dim, : fw * self.dim]
            frame = frame.reshape(
                self.dim, fh, self.dim, fw
            ).mean(axis=(1, 3))
        else:  # upscale-needed fallback: nearest
            ys = (np.arange(self.dim) * h // self.dim).clip(0, h - 1)
            xs = (np.arange(self.dim) * w // self.dim).clip(0, w - 1)
            frame = frame[ys][:, xs]
        return frame.astype(np.uint8)[:, :, None]


class EpisodicLifeEnv(gym.Wrapper):
    """End episode on life loss (reference EpisodicLifeEnv)."""

    def __init__(self, env):
        super().__init__(env)
        self.lives = 0
        self.was_real_done = True

    def step(self, action):
        obs, reward, term, trunc, info = self.env.step(action)
        self.was_real_done = term or trunc
        lives = self.env.unwrapped.ale.lives()
        if 0 < lives < self.lives:
            term = True
        self.lives = lives
        return obs, reward, term, trunc, info

    def reset(self, **kwargs):
        if self.was_real_done:
            obs, info = self.env.reset(**kwargs)
        else:
            obs, _, _, _, info = self.env.step(0)
        self.lives = self.env.unwrapped.ale.lives()
        return obs, info


class NoopResetEnv(gym.Wrapper):
    def __init__(self, env, noop_max: int = 30):
        super().__init__(env)
        self.noop_max = noop_max
        # own generator (RTA004): the noop count must not ride the
        # interpreter-global stream any import can perturb; a seed
        # passed through reset(seed=...) pins it per worker
        self._noop_rng = np.random.default_rng()

    def reset(self, **kwargs):
        if kwargs.get("seed") is not None:
            self._noop_rng = np.random.default_rng(kwargs["seed"])
        obs, info = self.env.reset(**kwargs)
        noops = int(self._noop_rng.integers(1, self.noop_max + 1))
        for _ in range(noops):
            obs, _, term, trunc, info = self.env.step(0)
            if term or trunc:
                obs, info = self.env.reset(**kwargs)
        return obs, info


def wrap_deepmind(env, dim: int = 84, framestack: bool = True):
    """Reference atari_wrappers.py wrap_deepmind."""
    if is_atari(env):
        env = NoopResetEnv(env, noop_max=30)
        env = MaxAndSkipEnv(env, skip=4)
        env = EpisodicLifeEnv(env)
    env = WarpFrame(env, dim)
    env = ClipRewardEnv(env)
    if framestack:
        env = FrameStack(env, 4)
    return env
