"""PolicyServerInput: serve actions to external envs over HTTP.

Counterpart of the reference's ``rllib/env/policy_server_input.py:26``:
an input reader the algorithm samples from — external environment
processes connect via :class:`~ray_tpu.env.policy_client.PolicyClient`,
request actions (computed on-policy here), log rewards, and finish
episodes; completed episodes become postprocessed SampleBatches the
training loop consumes like any sampler output.

Wire-up (reference examples/serving pattern):

    config.offline_data(input_=lambda ioctx: PolicyServerInput(
        ioctx, "127.0.0.1", 9900))

Transport is stdlib HTTP + JSON (obs/actions as nested lists) — no
external deps, adequate for the control-rate traffic of external envs.
"""

from __future__ import annotations

import json
import queue
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.data.sample_batch import SampleBatch
from ray_tpu.evaluation.metrics import RolloutMetrics


class _EpisodeState:
    __slots__ = ("rows", "pending", "total_reward", "training")

    def __init__(self, training: bool = True):
        self.rows: List[Dict] = []
        self.pending: Optional[Dict] = None  # row awaiting its reward
        self.total_reward = 0.0
        self.training = training


class PolicyServerInput:
    """reference policy_server_input.py:26 (input-reader API: next())."""

    def __init__(self, ioctx, address: str, port: int):
        self.worker = getattr(ioctx, "worker", None)
        policy_map = getattr(self.worker, "policy_map", None) or {}
        from ray_tpu.data.sample_batch import DEFAULT_POLICY_ID

        self.policy = policy_map.get(DEFAULT_POLICY_ID) or next(
            iter(policy_map.values())
        )
        if getattr(self.policy, "is_recurrent", False):
            raise ValueError(
                "PolicyServerInput does not support recurrent "
                "policies yet: per-episode RNN state is not tracked "
                "across GET_ACTION calls (reference "
                "policy_server_input.py has the same limitation for "
                "remote inference)"
            )
        # the same obs pipeline the SyncSampler applies (_transform):
        # preprocessor (one-hot/flatten for non-Box spaces — the policy
        # was built on the preprocessed space) then observation filter
        self.preprocessor = getattr(self.worker, "preprocessor", None)
        filters = getattr(self.worker, "filters", None) or {}
        self.obs_filter = filters.get(DEFAULT_POLICY_ID)
        self._episodes: Dict[str, _EpisodeState] = {}
        self._lock = threading.Lock()
        # the observation filter is stateful (running mean/std):
        # concurrent handler threads must not interleave its updates
        self._filter_lock = threading.Lock()
        self._batches: "queue.Queue" = queue.Queue()
        self._metrics: List[RolloutMetrics] = []

        server_self = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence per-request spam
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(length))
                    out = server_self._handle(req)
                    blob = json.dumps(out).encode()
                    self.send_response(200)
                except Exception as e:
                    blob = json.dumps({"error": repr(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

        self._server = ThreadingHTTPServer((address, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    # -- protocol ---------------------------------------------------------

    def _transform(self, obs) -> np.ndarray:
        from ray_tpu.evaluation.sampler import transform_obs

        with self._filter_lock:
            return transform_obs(
                self.preprocessor, self.obs_filter, obs
            )

    def _handle(self, req: Dict) -> Dict:
        cmd = req["command"]
        if cmd == "START_EPISODE":
            eid = req.get("episode_id") or uuid.uuid4().hex[:12]
            with self._lock:
                self._episodes[eid] = _EpisodeState(
                    training=req.get("training_enabled", True)
                )
            return {"episode_id": eid}
        ep = self._episodes.get(req["episode_id"])
        if ep is None:
            raise KeyError(f"unknown episode {req['episode_id']}")
        if cmd == "GET_ACTION":
            obs = self._transform(np.asarray(req["observation"]))
            action, _, extra = self.policy.compute_single_action(
                obs, explore=ep.training
            )
            row = {
                SampleBatch.OBS: obs,
                SampleBatch.ACTIONS: np.asarray(action),
                SampleBatch.REWARDS: np.float32(0.0),
                SampleBatch.TERMINATEDS: np.bool_(False),
                SampleBatch.TRUNCATEDS: np.bool_(False),
            }
            for k, v in extra.items():
                row[k] = np.asarray(v)
            with self._lock:
                self._finish_pending(ep, obs)
                ep.pending = row
            return {"action": np.asarray(action).tolist()}
        if cmd == "LOG_RETURNS":
            with self._lock:
                if ep.pending is not None:
                    ep.pending[SampleBatch.REWARDS] = np.float32(
                        float(ep.pending[SampleBatch.REWARDS])
                        + float(req["reward"])
                    )
                ep.total_reward += float(req["reward"])
            return {}
        if cmd == "END_EPISODE":
            obs = self._transform(np.asarray(req["observation"]))
            truncated = bool(req.get("truncated", False))
            # build under the lock, postprocess (GAE = a model forward)
            # outside it so concurrent envs aren't stalled
            with self._lock:
                self._finish_pending(
                    ep, obs, done=True, truncated=truncated
                )
                batch = self._build_episode_batch(
                    req["episode_id"], ep
                )
            if batch is not None:
                self._postprocess_and_enqueue(batch)
            return {}
        raise ValueError(f"unknown command {cmd!r}")

    def _finish_pending(
        self,
        ep: _EpisodeState,
        next_obs,
        done: bool = False,
        truncated: bool = False,
    ) -> None:
        if ep.pending is None:
            return
        row = ep.pending
        row[SampleBatch.NEXT_OBS] = np.asarray(next_obs, np.float32)
        if done:
            # truncation (time limit) keeps TERMINATEDS False so GAE
            # bootstraps V(s_T) instead of zero (sampler parity)
            row[SampleBatch.TERMINATEDS] = np.bool_(not truncated)
            row[SampleBatch.TRUNCATEDS] = np.bool_(truncated)
        ep.rows.append(row)
        ep.pending = None

    def _build_episode_batch(
        self, eid: str, ep: _EpisodeState
    ) -> Optional[SampleBatch]:
        """Lock-held: detach the episode and assemble its columns."""
        self._episodes.pop(eid, None)
        self._metrics.append(
            RolloutMetrics(len(ep.rows), ep.total_reward)
        )
        if not ep.rows or not ep.training:
            return None
        cols: Dict[str, np.ndarray] = {}
        for k in ep.rows[0].keys():
            cols[k] = np.stack([r[k] for r in ep.rows])
        cols[SampleBatch.EPS_ID] = np.full(
            len(ep.rows), abs(hash(eid)) % (2**31), np.int64
        )
        return SampleBatch(cols)

    def _postprocess_and_enqueue(self, batch: SampleBatch) -> None:
        from ray_tpu.evaluation.sampler import postprocess_batch

        self._batches.put(postprocess_batch(self.policy, batch))

    # -- input-reader API -------------------------------------------------

    def next(self) -> SampleBatch:
        """Block until an episode's batch is available (reference
        PolicyServerInput.next blocks on its queue the same way)."""
        return self._batches.get()

    def get_metrics(self) -> List[RolloutMetrics]:
        with self._lock:
            out = self._metrics
            self._metrics = []
        return out

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
