"""EnvContext: env config dict + worker placement info
(reference ``rllib/env/env_context.py``)."""

from __future__ import annotations


class EnvContext(dict):
    def __init__(
        self,
        env_config: dict | None = None,
        worker_index: int = 0,
        num_workers: int = 0,
        vector_index: int = 0,
        remote: bool = False,
    ):
        super().__init__(env_config or {})
        self.worker_index = worker_index
        self.num_workers = num_workers
        self.vector_index = vector_index
        self.remote = remote

    def copy_with_overrides(
        self,
        env_config: dict | None = None,
        worker_index: int | None = None,
        num_workers: int | None = None,
        vector_index: int | None = None,
    ) -> "EnvContext":
        return EnvContext(
            env_config if env_config is not None else dict(self),
            worker_index if worker_index is not None else self.worker_index,
            num_workers if num_workers is not None else self.num_workers,
            vector_index if vector_index is not None else self.vector_index,
        )
