"""PolicyClient: the external-env side of the serving pair.

Counterpart of the reference's ``rllib/env/policy_client.py:59``: an
environment running anywhere (a game process, a simulator fleet, a web
service) drives its episodes against a PolicyServerInput over HTTP —
start_episode / get_action / log_returns / end_episode."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

import numpy as np


class PolicyClient:
    """reference policy_client.py:59 (remote inference mode)."""

    def __init__(self, address: str, timeout: float = 60.0):
        if not address.startswith("http"):
            address = f"http://{address}"
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _call(self, payload: dict) -> dict:
        req = urllib.request.Request(
            self.address,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout
            ) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # surface the server-side diagnostic from the error body
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:
                detail = ""
            raise RuntimeError(
                f"policy server error {e.code}: {detail}"
            ) from None

    def start_episode(
        self,
        episode_id: Optional[str] = None,
        training_enabled: bool = True,
    ) -> str:
        return self._call(
            {
                "command": "START_EPISODE",
                "episode_id": episode_id,
                "training_enabled": training_enabled,
            }
        )["episode_id"]

    def get_action(self, episode_id: str, observation) -> np.ndarray:
        out = self._call(
            {
                "command": "GET_ACTION",
                "episode_id": episode_id,
                "observation": np.asarray(observation).tolist(),
            }
        )
        return np.asarray(out["action"])

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._call(
            {
                "command": "LOG_RETURNS",
                "episode_id": episode_id,
                "reward": float(reward),
            }
        )

    def end_episode(
        self, episode_id: str, observation, truncated: bool = False
    ) -> None:
        """``truncated=True`` marks a time-limit end (the server keeps
        TERMINATEDS False so GAE bootstraps V(s_T))."""
        self._call(
            {
                "command": "END_EPISODE",
                "episode_id": episode_id,
                "observation": np.asarray(observation).tolist(),
                "truncated": bool(truncated),
            }
        )
