from ray_tpu.env.env_context import EnvContext
from ray_tpu.env.vector_env import VectorEnv
from ray_tpu.env.jax_env import (
    ArraySpec,
    JaxVectorEnv,
    JaxVectorEnvAdapter,
)
from ray_tpu.env.multi_agent_env import MultiAgentEnv, make_multi_agent
from ray_tpu.env.registry import register_env, get_env_creator

__all__ = [
    "ArraySpec",
    "EnvContext",
    "JaxVectorEnv",
    "JaxVectorEnvAdapter",
    "VectorEnv",
    "MultiAgentEnv",
    "make_multi_agent",
    "register_env",
    "get_env_creator",
]
