"""JaxVectorEnv: the JAX-native vectorized-env API of the device
rollout lane (docs/pipeline.md "two rollout lanes").

For environments expressible as pure JAX functions (classic control,
gridworlds, synthetic traffic, pong_lite), rollouts don't need CPU
actors at all: ``execution/jax_rollout.py`` lowers
``policy.compute_actions → env.step → trajectory buffer`` as ONE jit'd
batch-sharded program on the learner mesh (the Anakin/Brax "everything
on device" pattern), so the hot path ships zero rollout bytes over
H2D. The CPU Ray-actor lane stays the default for everything else; the
two lanes share SampleBatch semantics and a fixed-seed parity contract
(tests/test_jax_env.py).

The API is three pure functions over an explicit per-env state pytree
(a dict of arrays; the carried PRNG key lives inside it):

  - ``init(key) -> state``          fresh per-env state from a PRNG key
  - ``reset(state) -> (state, obs)``  begin an episode, consuming the
    state's carried key stream (auto-reset draws come from here)
  - ``step(state, action) -> (state, obs, reward, terminated,
    truncated)``  one transition, NO auto-reset

Auto-reset is deliberately NOT part of the env: both lanes implement
it on top of ``reset`` in one documented place each, so the
terminal-observation contract cannot drift between them:

  **Terminal-observation contract** (matches the host
  ``VectorEnv``/``SyncSampler`` lane exactly — audited in
  tests/test_jax_env.py): at a step where ``terminated | truncated``,
  the row's NEXT_OBS is the env's FINAL (pre-reset) observation; the
  episode's successor row's OBS is the RESET observation of the new
  episode, drawn from the state's carried key stream. GAE bootstraps 0
  across ``terminated`` and V(final obs) across ``truncated``
  (``ops/gae.compute_gae_fragment``).

Shapes/dtypes are static: ``obs_spec``/``action_spec`` describe one
env's observation and action arrays; ``observation_space``/
``action_space`` expose the equivalent gymnasium spaces so the host
lane (policy construction, preprocessors) sees a normal env.

``JaxVectorEnvAdapter`` bridges a JaxVectorEnv into the host lane's
:class:`~ray_tpu.env.vector_env.VectorEnv` protocol — it steps ALL
sub-envs in one jitted vmapped call per ``vector_step`` (the same
functions the device lane scans over, same per-env key streams), which
is what makes the fixed-seed parity test possible: both lanes run
literally the same dynamics.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np


class ArraySpec(NamedTuple):
    """Static shape/dtype of one per-env array (no batch dim)."""

    shape: Tuple[int, ...]
    dtype: Any
    # Discrete action count (None for continuous/box specs)
    num_values: Optional[int] = None


class JaxVectorEnv:
    """Base class for JAX-native envs (see module docstring).

    Subclasses implement :meth:`init`, :meth:`reset`, :meth:`step`
    over ONE env's state (the engines vmap them), and set
    ``obs_spec`` / ``action_spec``.
    """

    obs_spec: ArraySpec
    action_spec: ArraySpec

    def __init__(self, config: Optional[Dict] = None):
        self.config = dict(config or {})

    # -- pure functions (single env; engines vmap) ----------------------

    def init(self, key):
        """Fresh per-env state pytree from a PRNG key. The state must
        carry the key (conventionally ``state["key"]``) — ``reset``
        and any stochastic ``step`` draw from it."""
        raise NotImplementedError

    def reset(self, state):
        """Begin a new episode using (and advancing) the state's
        carried key. Returns ``(state, obs)``."""
        raise NotImplementedError

    def step(self, state, action):
        """One transition, NO auto-reset:
        ``(state, obs, reward, terminated, truncated)`` with ``obs``
        the post-step (possibly terminal) observation, ``reward``
        float32, ``terminated``/``truncated`` bool scalars."""
        raise NotImplementedError

    # -- gym-facing surface (host lane / policy construction) ------------

    def close(self) -> None:
        """gym-API parity; pure-function envs hold no resources."""

    @property
    def observation_space(self):
        import gymnasium as gym

        spec = self.obs_spec
        if np.dtype(spec.dtype) == np.uint8:
            return gym.spaces.Box(0, 255, spec.shape, np.uint8)
        return gym.spaces.Box(
            -np.inf, np.inf, spec.shape, np.dtype(spec.dtype).type
        )

    @property
    def action_space(self):
        import gymnasium as gym

        spec = self.action_spec
        if spec.num_values is not None:
            return gym.spaces.Discrete(spec.num_values)
        return gym.spaces.Box(
            -1.0, 1.0, spec.shape, np.dtype(spec.dtype).type
        )


def env_keys(seed: Optional[int], num_envs: int):
    """The per-env PRNG keys BOTH lanes seed from: env ``i`` gets
    ``PRNGKey(seed + i)`` (mirroring the host
    ``_VectorizedGymEnv.vector_reset`` convention of ``seed + i``).
    ``None`` seeds default to 0 so the two lanes cannot diverge on the
    unseeded path either."""
    import jax

    base = 0 if seed is None else int(seed)
    return jax.numpy.stack(
        [jax.random.PRNGKey(base + i) for i in range(num_envs)]
    )


def tree_where(mask, a, b):
    """Per-leaf ``where(mask, a, b)`` with the (N,) mask broadcast
    over each leaf's trailing dims — the auto-reset selector."""
    import jax
    import jax.numpy as jnp

    def sel(x, y):
        m = mask.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(m, x, y)

    return jax.tree_util.tree_map(sel, a, b)


class JaxVectorEnvAdapter:
    """Host-lane bridge: a :class:`JaxVectorEnv` exposed through the
    :class:`~ray_tpu.env.vector_env.VectorEnv` protocol the samplers
    drive. One jitted vmapped ``step`` call advances every sub-env per
    ``vector_step``; ``reset_at`` resets a single slot from its own
    carried key stream — the exact auto-reset semantics of the device
    lane (module docstring), so fixed-seed trajectories match the
    device rollout engine's bit for bit on the same backend."""

    def __init__(
        self,
        env: JaxVectorEnv,
        num_envs: int,
        seed: Optional[int] = None,
    ):
        import jax

        self.jax_env = env
        self.num_envs = int(num_envs)
        self.observation_space = env.observation_space
        self.action_space = env.action_space
        self._seed = seed
        self._init_b = jax.jit(jax.vmap(env.init))
        self._reset_b = jax.jit(jax.vmap(env.reset))
        self._step_b = jax.jit(jax.vmap(env.step))
        self._reset_1 = jax.jit(env.reset)
        self._state = None

    # -- VectorEnv protocol ----------------------------------------------

    def vector_reset(self):
        keys = env_keys(self._seed, self.num_envs)
        self._state = self._init_b(keys)
        self._state, obs = self._reset_b(self._state)
        obs = np.asarray(obs)
        return [obs[i] for i in range(self.num_envs)], [
            {} for _ in range(self.num_envs)
        ]

    def reset_at(self, index: int):
        import jax

        sub = jax.tree_util.tree_map(
            lambda x: x[index], self._state
        )
        sub, obs = self._reset_1(sub)
        self._state = jax.tree_util.tree_map(
            lambda full, s: full.at[index].set(s), self._state, sub
        )
        return np.asarray(obs), {}

    def vector_step(self, actions):
        import jax.numpy as jnp

        act = jnp.asarray(np.stack([np.asarray(a) for a in actions]))
        self._state, obs, reward, term, trunc = self._step_b(
            self._state, act
        )
        obs = np.asarray(obs)
        reward = np.asarray(reward)
        term = np.asarray(term)
        trunc = np.asarray(trunc)
        return (
            [obs[i] for i in range(self.num_envs)],
            [float(reward[i]) for i in range(self.num_envs)],
            [bool(term[i]) for i in range(self.num_envs)],
            [bool(trunc[i]) for i in range(self.num_envs)],
            [{} for _ in range(self.num_envs)],
        )

    def get_sub_environments(self):
        return []
