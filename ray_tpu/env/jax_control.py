"""JAX-native classic-control / gridworld reference envs for the
device rollout lane (docs/pipeline.md).

``CartPoleJax`` is the classic-control reference: gymnasium
CartPole-v1 dynamics (Euler-integrated cart-pole, same constants and
termination bounds) as pure JAX functions — the cheap, well-understood
env the lane-parity tests and benchmarks run on. ``GridRoomsJax`` is a
small stochastic-start gridworld (four rooms, goal reward 1, step cost
0) exercising integer state + discrete dynamics under the same API.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.env.jax_env import ArraySpec, JaxVectorEnv


class CartPoleJax(JaxVectorEnv):
    """gymnasium CartPole-v1, jittable (same physics constants,
    ±0.05 uniform reset, |x| > 2.4 / |θ| > 12° termination, reward 1
    per step, truncation at ``max_steps`` — 500 like the gym
    registration, configurable)."""

    obs_spec = ArraySpec((4,), np.float32)
    action_spec = ArraySpec((), np.int32, num_values=2)

    _GRAVITY = 9.8
    _MASSCART = 1.0
    _MASSPOLE = 0.1
    _LENGTH = 0.5  # half pole length
    _FORCE_MAG = 10.0
    _TAU = 0.02
    _THETA_LIMIT = 12 * 2 * np.pi / 360
    _X_LIMIT = 2.4

    def __init__(self, config: Optional[Dict] = None):
        super().__init__(config)
        self.max_steps = int(self.config.get("max_steps", 500))

    def init(self, key):
        import jax.numpy as jnp

        return {
            "key": key,
            "s": jnp.zeros(4, jnp.float32),
            "steps": jnp.int32(0),
        }

    def reset(self, state):
        import jax

        key, sk = jax.random.split(state["key"])
        s = jax.random.uniform(
            sk, (4,), minval=-0.05, maxval=0.05
        ).astype("float32")
        state = {"key": key, "s": s, "steps": state["steps"] * 0}
        return state, s

    def step(self, state, action):
        import jax.numpy as jnp

        x, x_dot, theta, theta_dot = (
            state["s"][0],
            state["s"][1],
            state["s"][2],
            state["s"][3],
        )
        force = jnp.where(
            action == 1,
            jnp.float32(self._FORCE_MAG),
            jnp.float32(-self._FORCE_MAG),
        )
        costh = jnp.cos(theta)
        sinth = jnp.sin(theta)
        total_mass = self._MASSCART + self._MASSPOLE
        polemass_length = self._MASSPOLE * self._LENGTH
        temp = (
            force + polemass_length * theta_dot**2 * sinth
        ) / total_mass
        theta_acc = (self._GRAVITY * sinth - costh * temp) / (
            self._LENGTH
            * (4.0 / 3.0 - self._MASSPOLE * costh**2 / total_mass)
        )
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        x = x + self._TAU * x_dot
        x_dot = x_dot + self._TAU * x_acc
        theta = theta + self._TAU * theta_dot
        theta_dot = theta_dot + self._TAU * theta_acc
        s = jnp.stack([x, x_dot, theta, theta_dot]).astype(jnp.float32)
        steps = state["steps"] + 1
        terminated = (jnp.abs(x) > self._X_LIMIT) | (
            jnp.abs(theta) > self._THETA_LIMIT
        )
        truncated = steps >= self.max_steps
        state = {"key": state["key"], "s": s, "steps": steps}
        return (
            state,
            s,
            jnp.float32(1.0),
            terminated,
            truncated,
        )


class GridRoomsJax(JaxVectorEnv):
    """Four-rooms gridworld (``size`` × ``size``, walls on the mid row/
    column with door gaps): start uniformly in the top-left room, goal
    at the bottom-right corner (+1, terminate), 4 cardinal actions,
    truncation at ``max_steps``. Obs is the (row, col) position scaled
    to [0, 1]² float32 — MLP-friendly without one-hot plumbing."""

    action_spec = ArraySpec((), np.int32, num_values=4)
    obs_spec = ArraySpec((2,), np.float32)

    def __init__(self, config: Optional[Dict] = None):
        super().__init__(config)
        self.size = int(self.config.get("size", 9))
        self.max_steps = int(self.config.get("max_steps", 100))
        if self.size % 2 == 0:
            raise ValueError("GridRoomsJax needs an odd size")

    def _wall(self, r, c):
        import jax.numpy as jnp

        mid = self.size // 2
        door = mid // 2
        on_mid = (r == mid) | (c == mid)
        # four door gaps, one per wall arm
        gap = (
            ((r == mid) & ((c == door) | (c == self.size - 1 - door)))
            | ((c == mid) & ((r == door) | (r == self.size - 1 - door)))
        )
        return on_mid & ~gap

    def init(self, key):
        import jax.numpy as jnp

        return {
            "key": key,
            "pos": jnp.zeros(2, jnp.int32),
            "steps": jnp.int32(0),
        }

    def _obs(self, pos):
        import jax.numpy as jnp

        return pos.astype(jnp.float32) / float(self.size - 1)

    def reset(self, state):
        import jax

        key, sk = jax.random.split(state["key"])
        room = self.size // 2  # top-left room spans [0, mid)
        pos = jax.random.randint(sk, (2,), 0, room)
        state = {
            "key": key,
            "pos": pos.astype("int32"),
            "steps": state["steps"] * 0,
        }
        return state, self._obs(state["pos"])

    def step(self, state, action):
        import jax.numpy as jnp

        deltas = jnp.array(
            [[-1, 0], [1, 0], [0, -1], [0, 1]], jnp.int32
        )
        nxt = jnp.clip(
            state["pos"] + deltas[action], 0, self.size - 1
        )
        blocked = self._wall(nxt[0], nxt[1])
        pos = jnp.where(blocked, state["pos"], nxt)
        goal = jnp.all(pos == self.size - 1)
        steps = state["steps"] + 1
        state = {"key": state["key"], "pos": pos, "steps": steps}
        return (
            state,
            self._obs(pos),
            goal.astype(jnp.float32),
            goal,
            steps >= self.max_steps,
        )


from ray_tpu.env.registry import register_env  # noqa: E402

register_env("CartPoleJax-v0", lambda cfg: CartPoleJax(cfg))
register_env("GridRoomsJax-v0", lambda cfg: GridRoomsJax(cfg))
