"""PongLite: an in-repo Atari-shaped pixel control env.

The reference's throughput benchmarks run on ALE Pong/Breakout
(``rllib/tuned_examples/impala/pong-impala.yaml:1-5``,
``ppo/pong-ppo.yaml:1``); this image has no ALE (``ale_py`` absent), so
the end-to-end benchmarks use this stand-in with the same
observation/compute shape: 84x84 uint8 grayscale frames, Discrete(3)
actions, framestacked to (84, 84, 4) by the standard wrapper. The
learning problem is genuine (track the ball with the paddle from
pixels), so reward-vs-env-steps curves are meaningful, while the
per-step cost stays numpy-cheap like ALE's.

Dynamics: a ball bounces around the field; the agent moves a right-edge
paddle up/down/stay. Paddle contact rewards +1 and serves a new rally;
a miss rewards -1. An episode is ``rallies_per_episode`` rallies (21
like Pong), truncated at ``max_steps``. A tiny state-dependent serve
angle keeps the task non-degenerate (memorizing one trajectory doesn't
generalize; reading the ball's position does).
"""

from __future__ import annotations

import gymnasium as gym
import numpy as np

_SIZE = 84
_PADDLE_H = 12
_PADDLE_W = 2
_BALL = 2


class PongLite(gym.Env):
    metadata = {"render_modes": []}

    def __init__(self, config=None):
        config = config or {}
        self.rallies_per_episode = int(config.get("rallies", 21))
        self.max_steps = int(config.get("max_steps", 1000))
        self.paddle_speed = float(config.get("paddle_speed", 3.0))
        self._rng = np.random.default_rng(config.get("seed"))
        self.observation_space = gym.spaces.Box(
            0, 255, (_SIZE, _SIZE, 1), np.uint8
        )
        self.action_space = gym.spaces.Discrete(3)  # stay / up / down

    def _serve(self):
        self.bx = _SIZE * 0.3
        self.by = self._rng.uniform(_BALL, _SIZE - _BALL)
        angle = self._rng.uniform(-0.7, 0.7)
        speed = 2.2
        self.vx = speed * np.cos(angle)
        self.vy = speed * np.sin(angle)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.py = _SIZE / 2.0
        self.rallies = 0
        self.steps = 0
        self._serve()
        return self._render(), {}

    def step(self, action):
        self.steps += 1
        if action == 1:
            self.py -= self.paddle_speed
        elif action == 2:
            self.py += self.paddle_speed
        self.py = float(
            np.clip(self.py, _PADDLE_H / 2, _SIZE - _PADDLE_H / 2)
        )

        self.bx += self.vx
        self.by += self.vy
        # top/bottom and left-wall bounces
        if self.by <= _BALL or self.by >= _SIZE - _BALL:
            self.vy = -self.vy
            self.by = float(np.clip(self.by, _BALL, _SIZE - _BALL))
        if self.bx <= _BALL:
            self.vx = abs(self.vx)
            self.bx = float(_BALL)

        reward = 0.0
        paddle_x = _SIZE - _PADDLE_W - 1
        if self.bx >= paddle_x - _BALL:
            if abs(self.by - self.py) <= _PADDLE_H / 2 + _BALL:
                reward = 1.0
                self.vx = -abs(self.vx)
                # spin: contact point steers the return angle
                self.vy += 0.5 * (self.by - self.py) / (_PADDLE_H / 2)
                self.bx = float(paddle_x - _BALL)
            else:
                reward = -1.0
            self.rallies += 1
            if self.rallies < self.rallies_per_episode:
                self._serve()

        terminated = self.rallies >= self.rallies_per_episode
        truncated = self.steps >= self.max_steps
        return self._render(), reward, terminated, truncated, {}

    def _render(self):
        f = np.zeros((_SIZE, _SIZE, 1), np.uint8)
        by, bx = int(self.by), int(self.bx)
        f[
            max(0, by - _BALL) : by + _BALL,
            max(0, bx - _BALL) : bx + _BALL,
        ] = 255
        py = int(self.py)
        f[
            max(0, py - _PADDLE_H // 2) : py + _PADDLE_H // 2,
            _SIZE - _PADDLE_W - 1 : _SIZE - 1,
        ] = 180
        return f


def make_pong_lite(config=None):
    """PongLite with the standard 4-framestack (Atari obs shape)."""
    from ray_tpu.env.wrappers import FrameStack

    return FrameStack(PongLite(config), k=4)


from ray_tpu.env.registry import register_env  # noqa: E402

register_env("PongLite-v0", lambda cfg: make_pong_lite(cfg))
register_env("PongLiteFlat-v0", lambda cfg: PongLite(cfg))
