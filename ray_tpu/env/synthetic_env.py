"""SyntheticEnv: a near-zero-cost env for plumbing-bound benchmarks.

The reference isolates framework overhead from env cost with trivially
cheap envs (``rllib/env/tests``'s mock/random envs and the
``RandomEnv`` used in scale tests); this is the same tool for the
end-to-end benchmarks: ``env.step`` costs ~1 µs (index into a
pre-generated observation pool), so an e2e run on it measures what the
FRAMEWORK can move — sampler loop, action inference, object-store
shipping, learner queue — with the environment effectively free.

The observation is a small float vector and the reward a fixed function
of (obs, action), so policies still have non-degenerate gradients, but
nothing about the task is meant to be learned — throughput only.
"""

from __future__ import annotations

import gymnasium as gym
import numpy as np


class SyntheticEnv(gym.Env):
    metadata = {"render_modes": []}

    def __init__(self, config=None):
        config = config or {}
        self.obs_dim = int(config.get("obs_dim", 16))
        self.num_actions = int(config.get("num_actions", 4))
        self.episode_len = int(config.get("episode_len", 200))
        pool = int(config.get("pool", 256))
        rng = np.random.default_rng(int(config.get("seed", 0)))
        self._pool = rng.standard_normal(
            (pool, self.obs_dim)
        ).astype(np.float32)
        self._rewards = rng.standard_normal(pool).astype(np.float32)
        self.observation_space = gym.spaces.Box(
            -np.inf, np.inf, (self.obs_dim,), np.float32
        )
        self.action_space = gym.spaces.Discrete(self.num_actions)
        self._i = 0
        self._t = 0

    def reset(self, *, seed=None, options=None):
        self._t = 0
        self._i = (self._i + 1) % len(self._pool)
        return self._pool[self._i], {}

    def step(self, action):
        self._t += 1
        self._i = (self._i + int(action) + 1) % len(self._pool)
        truncated = self._t >= self.episode_len
        return (
            self._pool[self._i],
            float(self._rewards[self._i]),
            False,
            truncated,
            {},
        )


def _register():
    from ray_tpu.env.registry import register_env

    register_env("SyntheticFast-v0", lambda cfg: SyntheticEnv(cfg))


_register()
