"""BaseEnv: the unified poll/send async environment interface.

Counterpart of the reference's ``rllib/env/base_env.py`` (``BaseEnv
:18``, ``poll :121``, ``send_actions :146``): the lowest-level env API
every other env type converts down to — ``poll()`` returns whatever
observations are ready as ``{env_id: {agent_id: obs}}`` dicts and
``send_actions()`` pushes the matching actions. Gym envs, VectorEnv and
MultiAgentEnv all convert via :func:`convert_to_base_env`.

In this framework the samplers drive :class:`VectorEnv` directly (the
hot path stays dict-free for static batching), so BaseEnv is the
compatibility surface for ASYNC and external envs — anything whose
observations arrive irregularly — mirroring how reference users plug
custom async simulators in. Done episodes auto-reset like the
reference's ``_VectorEnvToBaseEnv``; the terminal observation is
surfaced in the same poll inside each agent's info dict:
``infos[env_id][agent_id]["__terminal_obs__"]``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.env.multi_agent_env import MultiAgentEnv
from ray_tpu.env.vector_env import VectorEnv

# Single-agent envs report under this agent key (reference
# base_env.py _DUMMY_AGENT_ID).
_DUMMY_AGENT_ID = "agent0"


class BaseEnv:
    """poll/send contract. All returned dicts are keyed
    ``{env_id: {agent_id: value}}``; ``dones[env_id]["__all__"]``
    marks episode end for the whole sub-env."""

    def poll(
        self,
    ) -> Tuple[Dict, Dict, Dict, Dict, Dict]:
        """→ (obs, rewards, terminateds, truncateds, infos) for every
        sub-env with data ready. Non-blocking w.r.t. envs that have
        nothing new."""
        raise NotImplementedError

    def send_actions(self, action_dict: Dict[Any, Dict]) -> None:
        """Push actions for the env_ids returned by the last poll."""
        raise NotImplementedError

    def try_reset(self, env_id) -> Optional[Dict]:
        """Force-reset one sub-env → its first obs dict (or None if
        unsupported)."""
        return None

    def get_sub_environments(self):
        return []

    def stop(self) -> None:
        for e in self.get_sub_environments():
            try:
                e.close()
            except Exception:
                pass


class _VectorEnvToBaseEnv(BaseEnv):
    """Synchronous VectorEnv behind the async contract (reference
    ``base_env.py`` VectorEnvWrapper): every poll has all sub-envs
    ready; dones auto-reset and the fresh obs appears in the SAME poll
    (the terminal obs rides infos)."""

    def __init__(self, vector_env: VectorEnv):
        self.vector_env = vector_env
        obs, infos = vector_env.vector_reset()
        self._pending = {
            i: (obs[i], 0.0, False, False, infos[i])
            for i in range(vector_env.num_envs)
        }
        self._awaiting_actions = False

    def poll(self):
        if self._awaiting_actions:
            raise RuntimeError(
                "poll() called twice without send_actions()"
            )
        self._awaiting_actions = True
        obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
        for i, (o, r, te, tr, info) in self._pending.items():
            obs[i] = {_DUMMY_AGENT_ID: o}
            rewards[i] = {_DUMMY_AGENT_ID: r}
            terms[i] = {_DUMMY_AGENT_ID: te, "__all__": te}
            truncs[i] = {_DUMMY_AGENT_ID: tr, "__all__": tr}
            infos[i] = {_DUMMY_AGENT_ID: info}
        return obs, rewards, terms, truncs, infos

    def send_actions(self, action_dict: Dict[Any, Dict]) -> None:
        if not self._awaiting_actions:
            raise RuntimeError("send_actions() without a poll()")
        self._awaiting_actions = False
        n = self.vector_env.num_envs
        actions = [
            action_dict[i][_DUMMY_AGENT_ID] for i in range(n)
        ]
        obs, rewards, terms, truncs, infos = (
            self.vector_env.vector_step(actions)
        )
        pending = {}
        for i in range(n):
            done = bool(terms[i]) or bool(truncs[i])
            info = dict(infos[i] or {})
            o = obs[i]
            if done:
                # auto-reset; terminal obs surfaces for bootstrapping
                info["__terminal_obs__"] = o
                o, _ = self.vector_env.reset_at(i)
            pending[i] = (
                o, float(rewards[i]), bool(terms[i]),
                bool(truncs[i]), info,
            )
        self._pending = pending

    def try_reset(self, env_id) -> Optional[Dict]:
        o, _ = self.vector_env.reset_at(env_id)
        self._pending[env_id] = (o, 0.0, False, False, {})
        return {_DUMMY_AGENT_ID: o}

    def get_sub_environments(self):
        return self.vector_env.get_sub_environments()


class _MultiAgentEnvToBaseEnv(BaseEnv):
    """MultiAgentEnv behind the async contract: per-agent dicts pass
    through; '__all__' drives the auto-reset."""

    def __init__(self, make_env: Callable[[int], MultiAgentEnv], num_envs: int):
        self.envs = [make_env(i) for i in range(num_envs)]
        self._pending = {}
        for i, e in enumerate(self.envs):
            obs, infos = e.reset()
            flags = {aid: False for aid in obs}
            flags["__all__"] = False
            self._pending[i] = (
                obs,
                {aid: 0.0 for aid in obs},
                dict(flags),
                dict(flags),
                infos,
            )
        self._awaiting_actions = False

    def poll(self):
        if self._awaiting_actions:
            raise RuntimeError(
                "poll() called twice without send_actions()"
            )
        self._awaiting_actions = True
        obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
        for i, (o, r, te, tr, info) in self._pending.items():
            obs[i], rewards[i] = o, r
            terms[i], truncs[i], infos[i] = te, tr, info
        return obs, rewards, terms, truncs, infos

    def send_actions(self, action_dict: Dict[Any, Dict]) -> None:
        if not self._awaiting_actions:
            raise RuntimeError("send_actions() without a poll()")
        self._awaiting_actions = False
        pending = {}
        for i, env in enumerate(self.envs):
            obs, rewards, terms, truncs, infos = env.step(
                action_dict[i]
            )
            done = bool(terms.get("__all__")) or bool(
                truncs.get("__all__")
            )
            if done:
                # per-agent terminal obs inside each agent's info,
                # matching the vector wrapper's nesting
                infos = {
                    aid: {
                        **(infos.get(aid) or {}),
                        "__terminal_obs__": obs.get(aid),
                    }
                    for aid in obs
                }
                obs, _ = env.reset()
            pending[i] = (obs, rewards, terms, truncs, infos)
        self._pending = pending

    def get_sub_environments(self):
        return list(self.envs)


def convert_to_base_env(
    env,
    *,
    make_env: Optional[Callable[[int], Any]] = None,
    num_envs: int = 1,
) -> BaseEnv:
    """Normalize any supported env type to BaseEnv (reference
    ``base_env.py convert_to_base_env``): BaseEnv passes through;
    VectorEnv and MultiAgentEnv wrap; a plain gym env vectorizes to
    ``num_envs`` copies via ``make_env`` (or deepcopy-free re-creation
    of the given instance when ``num_envs == 1``)."""
    if isinstance(env, BaseEnv):
        return env
    if isinstance(env, VectorEnv):
        return _VectorEnvToBaseEnv(env)
    if isinstance(env, MultiAgentEnv):
        creator = make_env or (lambda i: env)
        if make_env is None and num_envs > 1:
            raise ValueError(
                "vectorizing a MultiAgentEnv needs make_env"
            )
        return _MultiAgentEnvToBaseEnv(creator, num_envs)
    # plain gym env
    if make_env is None:
        if num_envs > 1:
            raise ValueError(
                "vectorizing a gym env needs make_env"
            )

        def make_env(i):  # noqa: F811 — single-instance fallback
            return env

    return _VectorEnvToBaseEnv(
        VectorEnv.vectorize_gym_envs(make_env, num_envs)
    )
