"""Dashboard-lite: cluster + training state over HTTP.

Counterpart of the reference's dashboard head + modules
(``dashboard/head.py:59``, ``dashboard/modules/{node,actor,job,...}``)
scoped to the single-host runtime: JSON endpoints for cluster state
(workers/actors/resources), the chrome-trace timeline, registered
metrics, and the latest training results, plus a small HTML index.

Start via ``DashboardLite()`` (any process that ran ray.init) or
``ray.init(dashboard=True)``."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

_RESULTS_LOCK = threading.Lock()
_RESULTS: List[Dict] = []  # ring of latest training results


def publish_result(result: Dict, keep: int = 200) -> None:
    """Algorithms push per-iteration results here (the reference's
    tune/job modules read equivalent state from the GCS)."""
    slim = {
        k: v
        for k, v in result.items()
        if isinstance(v, (int, float, str, bool))
    }
    slim["_time"] = time.time()
    with _RESULTS_LOCK:
        _RESULTS.append(slim)
        del _RESULTS[:-keep]


def _cluster_state() -> Dict:
    from ray_tpu.core import api as core_api

    rt = core_api._runtime
    if rt is None:
        return {"initialized": False}
    with rt.lock:
        workers = [
            {
                "worker_id": w.worker_id,
                "idle": w.idle,
                "dead": w.dead,
                "dedicated": w.dedicated,
                "ring_results": w.ring_results,
                "pid": w.proc.pid if w.proc else None,
            }
            for w in rt.pool
        ]
        actors = [
            {
                "actor_id": rec.actor_id[:12],
                "name": rec.name,
                "dead": rec.dead,
                "restarts": rec.restarts,
                "pid": rec.worker.proc.pid if rec.worker.proc else None,
            }
            for rec in rt.actors.values()
        ]
        pending = len(rt.pending)
    cluster = getattr(rt, "cluster", None)
    nodes = []
    if cluster is not None:
        for node in list(cluster.nodes.values()):
            nodes.append(
                {
                    "node_id": node.node_id,
                    "num_cpus": node.num_cpus,
                    "free_cpus": node.free_cpus(),
                    "actors": len(node.actor_ids),
                    "dead": node.dead,
                }
            )
    return {
        "initialized": True,
        "num_cpus": rt.num_cpus,
        "workers": workers,
        "actors": actors,
        "pending_tasks": pending,
        "nodes": nodes,
    }


_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><meta name="generator" content="dashboard-lite">
<title>ray_tpu dashboard</title>
<style>
:root{--surface:#fcfcfb;--panel:#ffffff;--ink:#0b0b0b;--ink2:#52514e;
      --line:#e4e3df;--series1:#2a78d6}
@media (prefers-color-scheme: dark){
:root{--surface:#1a1a19;--panel:#222221;--ink:#ffffff;--ink2:#c3c2b7;
      --line:#3a3a38;--series1:#3987e5}}
body{font:13px/1.5 system-ui,sans-serif;background:var(--surface);
     color:var(--ink);margin:0;padding:20px;max-width:1100px}
h1{font-size:17px;margin:0 0 4px}
h2{font-size:13px;color:var(--ink2);font-weight:600;margin:0 0 8px;
   text-transform:uppercase;letter-spacing:.04em}
.panel{background:var(--panel);border:1px solid var(--line);
       border-radius:8px;padding:14px 16px;margin:14px 0}
.tiles{display:flex;gap:14px;flex-wrap:wrap}
.tile{flex:1;min-width:120px}
.tile .v{font-size:24px;font-weight:650;font-variant-numeric:tabular-nums}
.tile .k{color:var(--ink2);font-size:12px}
table{border-collapse:collapse;width:100%;font-variant-numeric:tabular-nums}
th{color:var(--ink2);font-weight:600;text-align:left;font-size:12px}
th,td{padding:4px 10px 4px 0;border-bottom:1px solid var(--line)}
tr:last-child td{border-bottom:none}
a{color:var(--series1);text-decoration:none}
svg text{fill:var(--ink2);font:11px system-ui,sans-serif}
.muted{color:var(--ink2)}
.links{font-size:12px;color:var(--ink2)}
</style></head><body data-palette="#2a78d6">
<h1>ray_tpu</h1>
<div class="links">raw: <a href="/api/cluster">cluster</a> ·
<a href="/api/results">results</a> · <a href="/api/jobs">jobs</a> ·
<a href="/api/timeline">timeline</a> (chrome://tracing) ·
<a href="/metrics">metrics</a></div>
<div class="panel"><h2>Cluster</h2><div class="tiles" id="tiles"></div></div>
<div class="panel"><h2>Episode reward — latest run</h2>
<div id="chart" class="muted">waiting for results…</div></div>
<div class="panel"><h2>Recent results</h2>
<div id="results" class="muted">none yet</div></div>
<div class="panel"><h2>Jobs</h2><div id="jobs" class="muted">none</div></div>
<script>
const esc = s => String(s).replace(/[&<>"]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));
function tile(k, v){
  return `<div class="tile"><div class="v">${esc(v)}</div>` +
         `<div class="k">${esc(k)}</div></div>`;}
function sparkline(pts){
  // single series: no legend (the panel title names it), 2px line,
  // recessive grid, direct label on the last value, hover via
  // native <title> per sample point
  if (pts.length < 2) return "";
  const W=760, H=150, P=34;
  const xs = pts.map(p=>p[0]), ys = pts.map(p=>p[1]);
  const x0=Math.min(...xs), x1=Math.max(...xs);
  let y0=Math.min(...ys), y1=Math.max(...ys);
  if (y0===y1){y0-=1;y1+=1;}
  const X=v=>P+(W-2*P)*(v-x0)/(x1-x0||1);
  const Y=v=>H-P+(2*P-H)*(v-y0)/(y1-y0);
  const d = pts.map((p,i)=>(i?"L":"M")+X(p[0]).toFixed(1)+","
                    +Y(p[1]).toFixed(1)).join(" ");
  const dots = pts.map(p=>
    `<circle cx="${X(p[0]).toFixed(1)}" cy="${Y(p[1]).toFixed(1)}"`+
    ` r="7" fill="transparent"><title>iter ${p[0]}: `+
    `${p[1].toFixed(2)}</title></circle>`).join("");
  const last = pts[pts.length-1];
  return `<svg viewBox="0 0 ${W} ${H}" width="100%" role="img"
    aria-label="episode reward by iteration">
    <line x1="${P}" y1="${H-P}" x2="${W-P}" y2="${H-P}"
      stroke="var(--line)"/>
    <text x="${P}" y="${H-6}">${x0}</text>
    <text x="${W-P}" y="${H-6}" text-anchor="end">${x1} iters</text>
    <text x="4" y="${Y(y1)+4}">${y1.toFixed(1)}</text>
    <text x="4" y="${Y(y0)+4}">${y0.toFixed(1)}</text>
    <path d="${d}" fill="none" stroke="var(--series1)"
      stroke-width="2" stroke-linejoin="round"/>
    <circle cx="${X(last[0]).toFixed(1)}" cy="${Y(last[1]).toFixed(1)}"
      r="3.5" fill="var(--series1)"/>
    <text x="${Math.min(X(last[0])+6, W-2)}" y="${Y(last[1])+4}"
      >${last[1].toFixed(1)}</text>
    ${dots}</svg>`;}
async function refresh(){
  try{
    const c = await (await fetch("/api/cluster")).json();
    document.getElementById("tiles").innerHTML =
      tile("CPUs", c.num_cpus ?? 0) +
      tile("workers", (c.workers||[]).filter(w=>!w.dead).length) +
      tile("actors", (c.actors||[]).filter(a=>!a.dead).length) +
      tile("pending tasks", c.pending_tasks ?? 0) +
      tile("fleet nodes", (c.nodes||[]).filter(n=>!n.dead).length);
  }catch(e){}
  try{
    const rs = await (await fetch("/api/results")).json();
    if (rs.length){
      const cols = ["training_iteration","episode_reward_mean",
                    "num_env_steps_sampled","time_total_s"];
      const rows = rs.slice(-12).reverse().map(r =>
        "<tr>"+cols.map(k=>{
          let v = r[k]; if (typeof v === "number") v = v.toFixed(2);
          return `<td>${esc(v ?? "—")}</td>`;}).join("")+"</tr>");
      document.getElementById("results").innerHTML =
        `<table><tr>${cols.map(c=>`<th>${c}</th>`).join("")}</tr>`+
        rows.join("")+"</table>";
      const pts = rs.filter(r=>typeof r.episode_reward_mean==="number")
        .map(r=>[r.training_iteration??0, r.episode_reward_mean]);
      if (pts.length>1)
        document.getElementById("chart").innerHTML = sparkline(pts);
    }
  }catch(e){}
  try{
    const js = await (await fetch("/api/jobs")).json();
    if (js.length){
      document.getElementById("jobs").innerHTML =
        "<table><tr><th>id</th><th>status</th><th>entrypoint</th>"+
        "<th>logs</th></tr>"+js.map(j=>
        `<tr><td>${esc(j.submission_id||j.job_id)}</td>`+
        `<td>${esc(j.status)}</td><td>${esc(j.entrypoint||"")}</td>`+
        `<td><a href="/api/jobs/${esc(j.submission_id||j.job_id)}`+
        `/logs">logs</a></td></tr>`).join("")+"</table>";
    }
  }catch(e){}
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class DashboardLite:
    """reference dashboard/head.py:59, scoped to one host. Includes
    the job-submission REST surface (reference
    ``dashboard/modules/job/job_head.py``): POST /api/jobs submits,
    GET /api/jobs lists, GET /api/jobs/<id> gets status, GET
    /api/jobs/<id>/logs streams captured output, POST
    /api/jobs/<id>/stop stops."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 0, job_manager=None
    ):
        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, blob: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_POST(self):
                path = self.path.rstrip("/")
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b"{}"
                try:
                    req = json.loads(body or b"{}")
                    jm = dash.job_manager
                    if path == "/api/jobs":
                        packed = req.get("packed_runtime_env")
                        if packed and packed.get("archives"):
                            import base64

                            packed = dict(
                                packed,
                                archives=[
                                    {
                                        **a,
                                        "data": base64.b64decode(
                                            a["data"]
                                        ),
                                    }
                                    for a in packed["archives"]
                                ],
                            )
                        sid = jm.submit_job(
                            req["entrypoint"],
                            runtime_env=req.get("runtime_env"),
                            submission_id=req.get("submission_id"),
                            metadata=req.get("metadata"),
                            packed_runtime_env=packed,
                        )
                        blob = json.dumps(
                            {"submission_id": sid}
                        ).encode()
                    elif path.startswith("/api/jobs/") and path.endswith(
                        "/stop"
                    ):
                        sid = path[len("/api/jobs/"):-len("/stop")]
                        blob = json.dumps(
                            {"stopped": jm.stop_job(sid)}
                        ).encode()
                    else:
                        self._reply(404, b"{}", "application/json")
                        return
                    self._reply(200, blob, "application/json")
                except KeyError as e:
                    self._reply(
                        404,
                        json.dumps({"error": repr(e)}).encode(),
                        "application/json",
                    )
                except Exception as e:
                    self._reply(
                        500,
                        json.dumps({"error": repr(e)}).encode(),
                        "application/json",
                    )

            def do_GET(self):
                path = self.path.rstrip("/")
                try:
                    if path in ("", "/index.html"):
                        blob = _INDEX_HTML.encode()
                        ctype = "text/html"
                    elif path == "/api/jobs":
                        blob = json.dumps(
                            [
                                j.to_dict()
                                for j in dash.job_manager.list_jobs()
                            ]
                        ).encode()
                        ctype = "application/json"
                    elif path.startswith("/api/jobs/"):
                        sid = path[len("/api/jobs/"):]
                        try:
                            if sid.endswith("/logs"):
                                logs = dash.job_manager.get_job_logs(
                                    sid[: -len("/logs")]
                                )
                                blob = json.dumps(
                                    {"logs": logs}
                                ).encode()
                            else:
                                blob = json.dumps(
                                    dash.job_manager.get_job_info(
                                        sid
                                    ).to_dict()
                                ).encode()
                        except KeyError as e:
                            blob = json.dumps(
                                {"error": repr(e)}
                            ).encode()
                            self._reply(404, blob, "application/json")
                            return
                        ctype = "application/json"
                    elif path == "/api/cluster":
                        blob = json.dumps(_cluster_state()).encode()
                        ctype = "application/json"
                    elif path == "/api/results":
                        with _RESULTS_LOCK:
                            blob = json.dumps(_RESULTS).encode()
                        ctype = "application/json"
                    elif path == "/api/timeline":
                        import ray_tpu as ray

                        blob = json.dumps(ray.timeline()).encode()
                        ctype = "application/json"
                    elif path == "/metrics":
                        from ray_tpu.utils.metrics_exporter import (
                            format_prometheus,
                        )

                        blob = format_prometheus().encode()
                        ctype = "text/plain; version=0.0.4"
                    else:
                        self.send_response(404)
                        self.end_headers()
                        return
                    self.send_response(200)
                except Exception as e:
                    blob = json.dumps({"error": repr(e)}).encode()
                    ctype = "application/json"
                    self.send_response(500)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

        self._job_manager = job_manager
        self._job_lock = threading.Lock()
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def job_manager(self):
        if self._job_manager is None:
            with self._job_lock:
                if self._job_manager is None:
                    from ray_tpu.job.job_manager import JobManager

                    self._job_manager = JobManager()
        return self._job_manager

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._job_manager is not None:
            self._job_manager.shutdown()
