"""Dashboard-lite: cluster + training state over HTTP.

Counterpart of the reference's dashboard head + modules
(``dashboard/head.py:59``, ``dashboard/modules/{node,actor,job,...}``)
scoped to the single-host runtime: JSON endpoints for cluster state
(workers/actors/resources), the chrome-trace timeline, registered
metrics, and the latest training results, plus a small HTML index.

Start via ``DashboardLite()`` (any process that ran ray.init) or
``ray.init(dashboard=True)``."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

_RESULTS_LOCK = threading.Lock()
_RESULTS: List[Dict] = []  # ring of latest training results


def publish_result(result: Dict, keep: int = 200) -> None:
    """Algorithms push per-iteration results here (the reference's
    tune/job modules read equivalent state from the GCS)."""
    slim = {
        k: v
        for k, v in result.items()
        if isinstance(v, (int, float, str, bool))
    }
    slim["_time"] = time.time()
    with _RESULTS_LOCK:
        _RESULTS.append(slim)
        del _RESULTS[:-keep]


def _cluster_state() -> Dict:
    from ray_tpu.core import api as core_api

    rt = core_api._runtime
    if rt is None:
        return {"initialized": False}
    with rt.lock:
        workers = [
            {
                "worker_id": w.worker_id,
                "idle": w.idle,
                "dead": w.dead,
                "dedicated": w.dedicated,
                "ring_results": w.ring_results,
                "pid": w.proc.pid if w.proc else None,
            }
            for w in rt.pool
        ]
        actors = [
            {
                "actor_id": rec.actor_id[:12],
                "name": rec.name,
                "dead": rec.dead,
                "restarts": rec.restarts,
                "pid": rec.worker.proc.pid if rec.worker.proc else None,
            }
            for rec in rt.actors.values()
        ]
        pending = len(rt.pending)
    return {
        "initialized": True,
        "num_cpus": rt.num_cpus,
        "workers": workers,
        "actors": actors,
        "pending_tasks": pending,
    }


_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title></head>
<body style="font-family: monospace">
<h2>ray_tpu dashboard-lite</h2>
<ul>
<li><a href="/api/cluster">/api/cluster</a> — workers, actors, queue</li>
<li><a href="/api/results">/api/results</a> — latest training results</li>
<li><a href="/api/timeline">/api/timeline</a> — chrome-trace events
 (load in chrome://tracing)</li>
<li><a href="/metrics">/metrics</a> — Prometheus metrics</li>
<li><a href="/api/jobs">/api/jobs</a> — submitted jobs (POST to
 submit; /api/jobs/&lt;id&gt;, /&lt;id&gt;/logs, POST /&lt;id&gt;/stop)</li>
</ul>
</body></html>"""


class DashboardLite:
    """reference dashboard/head.py:59, scoped to one host. Includes
    the job-submission REST surface (reference
    ``dashboard/modules/job/job_head.py``): POST /api/jobs submits,
    GET /api/jobs lists, GET /api/jobs/<id> gets status, GET
    /api/jobs/<id>/logs streams captured output, POST
    /api/jobs/<id>/stop stops."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 0, job_manager=None
    ):
        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, blob: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def do_POST(self):
                path = self.path.rstrip("/")
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b"{}"
                try:
                    req = json.loads(body or b"{}")
                    jm = dash.job_manager
                    if path == "/api/jobs":
                        packed = req.get("packed_runtime_env")
                        if packed and packed.get("archives"):
                            import base64

                            packed = dict(
                                packed,
                                archives=[
                                    {
                                        **a,
                                        "data": base64.b64decode(
                                            a["data"]
                                        ),
                                    }
                                    for a in packed["archives"]
                                ],
                            )
                        sid = jm.submit_job(
                            req["entrypoint"],
                            runtime_env=req.get("runtime_env"),
                            submission_id=req.get("submission_id"),
                            metadata=req.get("metadata"),
                            packed_runtime_env=packed,
                        )
                        blob = json.dumps(
                            {"submission_id": sid}
                        ).encode()
                    elif path.startswith("/api/jobs/") and path.endswith(
                        "/stop"
                    ):
                        sid = path[len("/api/jobs/"):-len("/stop")]
                        blob = json.dumps(
                            {"stopped": jm.stop_job(sid)}
                        ).encode()
                    else:
                        self._reply(404, b"{}", "application/json")
                        return
                    self._reply(200, blob, "application/json")
                except KeyError as e:
                    self._reply(
                        404,
                        json.dumps({"error": repr(e)}).encode(),
                        "application/json",
                    )
                except Exception as e:
                    self._reply(
                        500,
                        json.dumps({"error": repr(e)}).encode(),
                        "application/json",
                    )

            def do_GET(self):
                path = self.path.rstrip("/")
                try:
                    if path in ("", "/index.html"):
                        blob = _INDEX_HTML.encode()
                        ctype = "text/html"
                    elif path == "/api/jobs":
                        blob = json.dumps(
                            [
                                j.to_dict()
                                for j in dash.job_manager.list_jobs()
                            ]
                        ).encode()
                        ctype = "application/json"
                    elif path.startswith("/api/jobs/"):
                        sid = path[len("/api/jobs/"):]
                        try:
                            if sid.endswith("/logs"):
                                logs = dash.job_manager.get_job_logs(
                                    sid[: -len("/logs")]
                                )
                                blob = json.dumps(
                                    {"logs": logs}
                                ).encode()
                            else:
                                blob = json.dumps(
                                    dash.job_manager.get_job_info(
                                        sid
                                    ).to_dict()
                                ).encode()
                        except KeyError as e:
                            blob = json.dumps(
                                {"error": repr(e)}
                            ).encode()
                            self._reply(404, blob, "application/json")
                            return
                        ctype = "application/json"
                    elif path == "/api/cluster":
                        blob = json.dumps(_cluster_state()).encode()
                        ctype = "application/json"
                    elif path == "/api/results":
                        with _RESULTS_LOCK:
                            blob = json.dumps(_RESULTS).encode()
                        ctype = "application/json"
                    elif path == "/api/timeline":
                        import ray_tpu as ray

                        blob = json.dumps(ray.timeline()).encode()
                        ctype = "application/json"
                    elif path == "/metrics":
                        from ray_tpu.utils.metrics_exporter import (
                            format_prometheus,
                        )

                        blob = format_prometheus().encode()
                        ctype = "text/plain; version=0.0.4"
                    else:
                        self.send_response(404)
                        self.end_headers()
                        return
                    self.send_response(200)
                except Exception as e:
                    blob = json.dumps({"error": repr(e)}).encode()
                    ctype = "application/json"
                    self.send_response(500)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

        self._job_manager = job_manager
        self._job_lock = threading.Lock()
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def job_manager(self):
        if self._job_manager is None:
            with self._job_lock:
                if self._job_manager is None:
                    from ray_tpu.job.job_manager import JobManager

                    self._job_manager = JobManager()
        return self._job_manager

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._job_manager is not None:
            self._job_manager.shutdown()
