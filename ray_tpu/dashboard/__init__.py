from ray_tpu.dashboard.dashboard import DashboardLite, publish_result

__all__ = ["DashboardLite", "publish_result"]
