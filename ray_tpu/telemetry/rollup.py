"""Per-iteration span roll-up: stage wall-times + overlap fraction.

The spans recorded by the instrumented hot path (see
docs/observability.md for the span map) are point measurements; this
module turns one iteration's window of them into the summary that
lands in ``train()`` results under ``info/telemetry``:

- per-stage *busy* time (union of that stage's span intervals clamped
  to the window — concurrent spans of one stage don't double-count);
- the **overlap fraction**: of the time the learn nest ran, how much
  of it sampling was also running. 1.0 = fully pipelined (the
  ``sample_prefetch`` promise), 0.0 = strictly serial — this is the
  number docs/pipeline.md previously said needed a profiler.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

Interval = Tuple[float, float]

# span-name prefixes -> stage buckets. Worker-side spans arrive with
# their own names via the result-message piggyback (core/api.py).
STAGE_PREFIXES: Dict[str, Tuple[str, ...]] = {
    "sample": ("rollout:", "sampler:", "sample:round"),
    "assemble": ("prefetch:assemble", "prefetch:deliver"),
    "transfer": ("feeder:transfer", "learn:transfer"),
    # learn:nest = the per-update SGD nest; learn:superstep = the
    # fused K-updates-per-dispatch program that replaces it on the
    # superstep path (without it, superstep runs reported learn_s 0)
    "learn": ("learn:nest", "learn:superstep"),
    # compiled-program execution intervals on the synthetic device
    # lanes (telemetry/device.py) — busy time of the device plane
    # itself, next to the host stages that feed it
    "device": ("device:",),
    # time lost to the resilience layer: fleet probe+recreate,
    # checkpoint restore, periodic checkpoint writes (recovery:* spans)
    "recovery": ("recovery:",),
}

# stages whose spans count as "sampling is running" for the overlap
# computation: the worker-side rollout execution only (driver-side
# harvest bookkeeping isn't the work we want to overlap with)
_SAMPLING_FOR_OVERLAP = ("rollout:", "sampler:")


def merge_intervals(
    intervals: Iterable[Interval],
) -> List[Interval]:
    """Union of possibly-overlapping [start, end) intervals."""
    ivs = sorted(
        (s, e) for s, e in intervals if e > s
    )
    out: List[Interval] = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def total(intervals: Sequence[Interval]) -> float:
    return sum(e - s for s, e in intervals)


def intersect(
    a: Sequence[Interval], b: Sequence[Interval]
) -> List[Interval]:
    """Intersection of two MERGED interval lists."""
    out: List[Interval] = []
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            out.append((s, e))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


def _clamped(
    spans: Iterable[dict], t0: float, t1: float, prefixes
) -> List[Interval]:
    out = []
    for s in spans:
        name = s.get("name", "")
        if not any(name.startswith(p) for p in prefixes):
            continue
        start = s.get("start")
        end = s.get("end") or start
        if start is None or end <= t0 or start >= t1:
            continue
        out.append((max(start, t0), min(end, t1)))
    return merge_intervals(out)


def late_stage_times(
    late_spans: Iterable[dict],
) -> Dict[str, float]:
    """Per-stage busy time of spans that arrived AFTER their own
    window settled (late cross-host harvest): full-duration union per
    stage, no window clamp — the window they belong to already rolled
    up without them, so the consumer credits them to its next window
    instead of dropping the time on the floor."""
    late_spans = list(late_spans)
    out: Dict[str, float] = {}
    for stage, prefixes in STAGE_PREFIXES.items():
        ivs = []
        for s in late_spans:
            name = s.get("name", "")
            if not any(name.startswith(p) for p in prefixes):
                continue
            start = s.get("start")
            end = s.get("end") or start
            if start is None or end is None:
                continue
            ivs.append((start, max(start, end)))
        out[stage] = total(merge_intervals(ivs))
    return out


def iteration_rollup(
    spans: Iterable[dict],
    t0: float,
    t1: float,
    late: Iterable[dict] = (),
) -> Dict[str, float]:
    """Summarize one iteration window ``[t0, t1]`` of finished spans.

    Returns ``{stage}_s`` busy times for each stage of
    :data:`STAGE_PREFIXES`, ``iteration_s``, and
    ``overlap_fraction`` = |learn ∩ sampling| / |learn| (0.0 when no
    learn span landed in the window).

    ``late`` names spans that were first harvested in THIS window but
    ended before it opened (their own window settled without them —
    the cross-host fleetview harvest can lag a full publish interval).
    Their full durations are credited to this window's stage totals
    via :func:`late_stage_times`, so the across-window sum matches an
    on-time harvest instead of silently losing the segments. The
    overlap fraction stays a pure in-window statement (late sampling
    can't retroactively overlap this window's learn)."""
    spans = list(spans)
    out: Dict[str, float] = {
        "iteration_s": max(0.0, t1 - t0)
    }
    late_times = late_stage_times(late) if late else {}
    merged: Dict[str, List[Interval]] = {}
    for stage, prefixes in STAGE_PREFIXES.items():
        merged[stage] = _clamped(spans, t0, t1, prefixes)
        out[f"{stage}_s"] = total(merged[stage]) + late_times.get(
            stage, 0.0
        )
    sampling = _clamped(spans, t0, t1, _SAMPLING_FOR_OVERLAP)
    learn = merged["learn"]
    learn_total = total(learn)
    out["overlap_fraction"] = (
        total(intersect(learn, sampling)) / learn_total
        if learn_total > 0
        else 0.0
    )
    return out
