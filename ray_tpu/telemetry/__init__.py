"""ray_tpu.telemetry — end-to-end run observability for the training
loop (docs/observability.md).

Stitches the two existing primitives — span tracing
(:mod:`ray_tpu.util.tracing`, the counterpart of the reference's
``tracing_helper.py:324,449`` span propagation) and process metrics
(:mod:`ray_tpu.utils.metrics` + the Prometheus ``MetricsServer``,
the counterpart of ``_private/metrics_agent.py:63``) — into one layer:

- :func:`init_from_config` / :func:`init` — config-driven activation
  (``AlgorithmConfig.telemetry(metrics_port=..., trace=...)``);
- :mod:`~ray_tpu.telemetry.metrics` — the aggregate metric catalog
  (throughput, queue depths, in-flight requests, compile cache, jax
  memory) the instrumented hot path feeds;
- :func:`iteration_rollup` — per-iteration stage wall-times and the
  rollout/learn **overlap fraction**, computed from spans and
  reported under ``info/telemetry`` in every ``train()`` result.
"""

from ray_tpu.telemetry import device  # noqa: F401
from ray_tpu.telemetry import metrics  # noqa: F401
from ray_tpu.telemetry.rollup import (  # noqa: F401
    STAGE_PREFIXES,
    intersect,
    iteration_rollup,
    merge_intervals,
)
from ray_tpu.telemetry.runtime import (  # noqa: F401
    TelemetryRuntime,
    enabled,
    init,
    init_from_config,
    runtime,
)

# imported last: fleetview pulls in tracing + the metric catalog above
# (its fleet/kv imports stay lazy, inside methods, to avoid a package
# cycle with ray_tpu.fleet)
from ray_tpu.telemetry import fleetview  # noqa: E402,F401
from ray_tpu.telemetry.fleetview import (  # noqa: E402,F401
    FleetAggregator,
    HostExporter,
)

__all__ = [
    "FleetAggregator",
    "HostExporter",
    "TelemetryRuntime",
    "STAGE_PREFIXES",
    "device",
    "enabled",
    "fleetview",
    "init",
    "init_from_config",
    "intersect",
    "iteration_rollup",
    "merge_intervals",
    "metrics",
    "runtime",
]
