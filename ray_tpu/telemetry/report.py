"""Flight-recorder report: render a run's trace + ledger as text.

::

    python -m ray_tpu.telemetry.report trace.json \
        [--ledger ledger.json] [--top 10] [--json]

``trace.json`` is what ``Algorithm.export_timeline`` (or
``tracing.export_chrome_trace``) wrote; ``ledger.json`` is an optional
``telemetry.device.dump()`` snapshot that adds FLOPs / MFU / HBM
columns the trace alone doesn't carry. Sections:

- **top programs by device time** — the ``device:`` lanes: execution
  count, total/mean busy time, and (with the ledger) per-execution
  FLOPs, MFU, HBM footprint;
- **recompiles with causes** — every ``jit:recompile`` event, with
  the forensics diff (which abstract leaf's shape/dtype moved);
- **stage busy / overlap breakdown** — the iteration-rollup math over
  the whole trace window (sample/assemble/transfer/learn/device busy
  seconds, rollout↔learn overlap fraction);
- **transfer lane** — the device_feed H2D lane: transfer count,
  busy seconds, payload bytes (from the spans' ``nbytes``).

``--json`` prints the same report as one JSON object (tests and
dashboards); default is aligned text for humans.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def _load_spans(trace_path: str) -> List[dict]:
    """Chrome-trace events back into the span-dict shape the rollup
    math consumes (seconds, not microseconds)."""
    with open(trace_path) as f:
        events = json.load(f).get("traceEvents", [])
    spans = []
    for e in events:
        if e.get("ph") != "X":
            continue
        start = e.get("ts", 0.0) / 1e6
        spans.append(
            {
                "name": e.get("name", ""),
                "start": start,
                "end": start + e.get("dur", 0.0) / 1e6,
                "pid": e.get("pid"),
                "tid": e.get("tid"),
                "attributes": {
                    k: v
                    for k, v in (e.get("args") or {}).items()
                    if k
                    not in ("trace_id", "span_id", "parent_id")
                },
            }
        )
    return spans


def build_report(
    trace_path: str,
    ledger_path: Optional[str] = None,
    top: int = 10,
) -> Dict[str, Any]:
    from ray_tpu.telemetry.rollup import iteration_rollup

    spans = _load_spans(trace_path)
    ledger = None
    if ledger_path:
        with open(ledger_path) as f:
            ledger = json.load(f)
    by_label: Dict[str, Dict[str, Any]] = {}
    recompiles: List[Dict[str, Any]] = []
    transfer = {"count": 0, "busy_s": 0.0, "bytes": 0.0}
    for s in spans:
        name = s["name"]
        dur = max(0.0, s["end"] - s["start"])
        if name.startswith("device:"):
            row = by_label.setdefault(
                name[len("device:"):],
                {"executions": 0, "device_time_s": 0.0},
            )
            row["executions"] += 1
            row["device_time_s"] += dur
        elif name == "jit:recompile":
            recompiles.append(
                {
                    "label": s["attributes"].get("label", "?"),
                    "cause": s["attributes"].get("cause"),
                }
            )
        elif name == "feeder:transfer":
            transfer["count"] += 1
            transfer["busy_s"] += dur
            transfer["bytes"] += float(
                s["attributes"].get("nbytes", 0) or 0
            )
    # graft ledger columns onto the trace's device rows (and pick up
    # programs the trace window missed entirely)
    ledger_rows = {
        p["label"]: p
        for p in (ledger or {}).get("programs", ())
    }
    for label, p in ledger_rows.items():
        row = by_label.setdefault(
            label,
            {
                "executions": p["executions"],
                "device_time_s": p["device_time_s"],
            },
        )
        row.update(
            flops=p.get("flops"),
            mfu=p.get("mfu"),
            bytes_accessed=p.get("bytes_accessed"),
            hbm_temp_bytes=(p.get("memory") or {}).get(
                "temp_bytes"
            ),
            recompiles=p.get("recompiles"),
            compile_time_s=p.get("compile_time_s"),
        )
    programs = [
        {"label": label, **row} for label, row in by_label.items()
    ]
    programs.sort(
        key=lambda r: r["device_time_s"], reverse=True
    )
    window = None
    rollup = None
    if spans:
        t0 = min(s["start"] for s in spans)
        t1 = max(s["end"] for s in spans)
        rollup = iteration_rollup(spans, t0, t1)
        window = {"start": t0, "end": t1, "wall_s": t1 - t0}
    report: Dict[str, Any] = {
        "trace": trace_path,
        "spans": len(spans),
        "window": window,
        "programs": programs[: max(1, int(top))],
        "programs_total": len(programs),
        "recompiles": recompiles,
        "stages": rollup,
        "transfer_lane": transfer,
    }
    if ledger:
        report["ledger"] = {
            "device_kind": ledger.get("device_kind"),
            "peak_flops_per_device": ledger.get(
                "peak_flops_per_device"
            ),
            "totals": ledger.get("totals"),
            "recompile_causes": ledger.get("recompile_causes"),
        }
    return report


def _fmt_num(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if abs(v) >= 1e9:
            return f"{v / 1e9:.2f}G{unit}"
        if abs(v) >= 1e6:
            return f"{v / 1e6:.2f}M{unit}"
        if abs(v) >= 1e3:
            return f"{v / 1e3:.2f}k{unit}"
        return f"{v:.4g}{unit}"
    return f"{v}{unit}"


def render_text(report: Dict[str, Any]) -> str:
    out: List[str] = []
    w = report.get("window") or {}
    out.append(
        f"== flight recorder: {report['trace']} "
        f"({report['spans']} spans, "
        f"{_fmt_num(w.get('wall_s'), 's')} window) =="
    )
    led = report.get("ledger")
    if led:
        tot = led.get("totals") or {}
        mfu = tot.get("mfu")
        out.append(
            f"device: {led.get('device_kind')}  "
            f"peak {_fmt_num(led.get('peak_flops_per_device'))}"
            "FLOP/s  aggregate MFU "
            + (f"{100 * mfu:.2f}%" if mfu else "-")
        )
    out.append("")
    out.append(
        f"-- top programs by device time "
        f"({report['programs_total']} total) --"
    )
    hdr = (
        f"{'program':44s} {'execs':>6s} {'busy_s':>9s} "
        f"{'mean_s':>9s} {'flops':>9s} {'mfu%':>6s} {'recomp':>6s}"
    )
    out.append(hdr)
    for p in report["programs"]:
        execs = p["executions"]
        busy = p["device_time_s"]
        mean = busy / execs if execs else 0.0
        mfu = p.get("mfu")
        out.append(
            f"{p['label'][:44]:44s} {execs:>6d} {busy:>9.4f} "
            f"{mean:>9.5f} {_fmt_num(p.get('flops')):>9s} "
            f"{(f'{100 * mfu:.2f}' if mfu else '-'):>6s} "
            f"{str(p.get('recompiles', '-')):>6s}"
        )
    out.append("")
    rec = report["recompiles"]
    out.append(f"-- recompiles ({len(rec)}) --")
    for r in rec:
        out.append(
            f"{r['label']}: {r.get('cause') or '(no cause recorded)'}"
        )
    causes = (led or {}).get("recompile_causes") or {}
    for label, cs in causes.items():
        for c in cs:
            out.append(
                f"[ledger] {label}: {c['cause']} x{c['count']}"
            )
    out.append("")
    st = report.get("stages")
    if st:
        out.append("-- stage busy / overlap --")
        for k in sorted(st):
            if k.endswith("_s") or k == "overlap_fraction":
                out.append(f"{k:24s} {st[k]:.4f}")
    tr = report.get("transfer_lane") or {}
    out.append("")
    out.append(
        f"-- transfer lane -- {tr.get('count', 0)} transfers, "
        f"{tr.get('busy_s', 0.0):.4f}s busy, "
        f"{_fmt_num(tr.get('bytes'), 'B')}"
    )
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.telemetry.report",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("trace", help="chrome trace JSON (export_timeline)")
    ap.add_argument(
        "--ledger",
        help="device-ledger JSON (telemetry.device.dump)",
    )
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument(
        "--json", action="store_true", help="emit JSON, not text"
    )
    args = ap.parse_args(argv)
    report = build_report(
        args.trace, ledger_path=args.ledger, top=args.top
    )
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
