"""Config-driven telemetry activation.

``AlgorithmConfig.telemetry(metrics_port=..., trace=...)`` lands in
``config["telemetry_config"]``; :func:`init_from_config` (called from
``Algorithm.setup``) turns it into a live runtime: a
:class:`~ray_tpu.utils.metrics_exporter.MetricsServer` scrape target
and/or span tracing via :mod:`ray_tpu.util.tracing`. The counterpart
of the reference's ``RAY_TRACING_ENABLED`` + per-node metrics agent
autostart (``_private/metrics_agent.py:63``).

One runtime per process: a second Algorithm in the same process
reuses the running server (ports are process-wide); tracing enable is
idempotent. ``RAY_TPU_TRACE=1`` remains the env-var override that
needs no config at all.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_LOCK = threading.Lock()
_RUNTIME: Optional["TelemetryRuntime"] = None


class TelemetryRuntime:
    """Live telemetry state for this process."""

    def __init__(
        self,
        *,
        metrics_port: Optional[int] = None,
        trace: bool = False,
        metrics_host: str = "127.0.0.1",
        device_ledger: Any = True,
    ):
        self.trace = bool(trace)
        self.metrics_server = None
        self.metrics_port: Optional[int] = None
        if metrics_port is not None:
            from ray_tpu.utils.metrics_exporter import MetricsServer

            self.metrics_server = MetricsServer(
                host=metrics_host, port=int(metrics_port)
            )
            self.metrics_port = self.metrics_server.port
        if self.trace:
            from ray_tpu.util import tracing

            tracing.enable()
        # compiled-program ledger (telemetry/device.py): on whenever
        # the runtime is — "light" keeps counters/forensics but skips
        # the cost/memory analysis (its one extra AOT compile per
        # traced signature); False leaves the dispatch path untouched
        self.device_ledger = device_ledger
        if device_ledger:
            from ray_tpu.telemetry import device as device_lib

            device_lib.enable(
                analyze=(device_ledger != "light")
            )

    def shutdown(self) -> None:
        global _RUNTIME
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
            self.metrics_server = None
        if self.trace:
            from ray_tpu.util import tracing

            tracing.disable()
        if self.device_ledger:
            from ray_tpu.telemetry import device as device_lib

            device_lib.disable()
        with _LOCK:
            if _RUNTIME is self:
                _RUNTIME = None


def runtime() -> Optional[TelemetryRuntime]:
    """The process's active runtime (None when telemetry is off)."""
    return _RUNTIME


def enabled() -> bool:
    return _RUNTIME is not None


def init(
    *,
    metrics_port: Optional[int] = None,
    trace: bool = False,
    metrics_host: str = "127.0.0.1",
    device_ledger: Any = True,
) -> TelemetryRuntime:
    """Start (or return the already-running) telemetry runtime."""
    global _RUNTIME
    with _LOCK:
        if _RUNTIME is not None:
            # upgrade in place: a later config may add tracing or a
            # scrape port the first runtime didn't ask for (and a
            # tracing.disable() elsewhere must not leave a trace=True
            # runtime silently dark — re-enable unconditionally)
            if trace:
                from ray_tpu.util import tracing

                tracing.enable()
                _RUNTIME.trace = True
            if device_ledger:
                from ray_tpu.telemetry import device as device_lib

                device_lib.enable(
                    analyze=(device_ledger != "light")
                )
                _RUNTIME.device_ledger = device_ledger
            if (
                metrics_port is not None
                and _RUNTIME.metrics_server is None
            ):
                from ray_tpu.utils.metrics_exporter import (
                    MetricsServer,
                )

                _RUNTIME.metrics_server = MetricsServer(
                    host=metrics_host, port=int(metrics_port)
                )
                _RUNTIME.metrics_port = (
                    _RUNTIME.metrics_server.port
                )
            return _RUNTIME
        _RUNTIME = TelemetryRuntime(
            metrics_port=metrics_port,
            trace=trace,
            metrics_host=metrics_host,
            device_ledger=device_ledger,
        )
        return _RUNTIME


def init_from_config(
    config: Dict[str, Any],
) -> Optional[TelemetryRuntime]:
    """Activate telemetry when ``config["telemetry_config"]`` asks for
    it. Returns the runtime, or None when the config leaves telemetry
    off (the default — zero threads, zero spans, null-span hot path)."""
    tc = (config or {}).get("telemetry_config") or {}
    metrics_port = tc.get("metrics_port")
    trace = bool(tc.get("trace", False))
    # device_ledger=True may activate telemetry alone (counters-only
    # runs that want the program ledger without spans or a scrape port)
    ledger_cfg = tc.get("device_ledger")
    if metrics_port is None and not trace and not ledger_cfg:
        return None
    if tc.get("peak_flops"):
        from ray_tpu.telemetry import device as device_lib

        device_lib.set_peak_flops(
            tc.get("peak_flops"), tc.get("peak_hbm_bytes_per_s")
        )
    return init(
        metrics_port=metrics_port,
        trace=trace,
        device_ledger=(
            True if ledger_cfg is None else ledger_cfg
        ),
    )
