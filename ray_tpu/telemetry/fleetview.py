"""ray_tpu.telemetry.fleetview — fleet-wide observability over the KV
plane (docs/observability.md "Fleet view").

PRs 3 and 13 built deep per-process observability; PR 17 made the
system a fleet of hosts that were each a blind silo. This module is
the per-node-agent → head aggregation pattern of the reference's
dashboard (``dashboard/``'s metrics agents reporting to the head),
reproduced natively on our own KV transport:

- every host runs a :class:`HostExporter`: a periodic publish of its
  Prometheus registry snapshot, a device-ledger digest, the span
  segments finished since the last tick, its recent collective
  drain-point arrivals, and a clock-offset handshake against the
  coordinator's KV clock (:meth:`KVClient.server_clock`);
- the coordinator host runs a :class:`FleetAggregator`: it merges the
  snapshots into ONE Prometheus exposition (``host=`` label on every
  series — counters SUM on a full-key collision, gauges last-write in
  sorted host order, histograms merge bucket-wise), renders a
  skew-corrected fleet chrome timeline (one lane group per host,
  device lanes included, the tracing child-clamp rule reused per
  host), and turns barrier/drain-point arrival records into
  **straggler attribution**:
  ``ray_tpu_fleet_barrier_wait_seconds{host,epoch}`` +
  ``ray_tpu_fleet_straggler_total{host}`` plus ``fleet:barrier`` spans
  naming the last arriver.

Skew model: the exporter measures ``offset = host_clock − kv_clock``
with an NTP-style midpoint handshake (the KV server runs on the
coordinator host, so its clock is the fleet's reference frame) and
ships it with every snapshot; the aggregator maps any host stamp into
the reference frame as ``t − offset`` before comparing across hosts.

Env knobs: ``RAY_TPU_FLEETVIEW_INTERVAL_S`` exporter cadence (2 s),
``RAY_TPU_FLEETVIEW_MAX_AGE_S`` snapshot staleness horizon at the
aggregator (15 s) — a host that stops publishing ages out of the
merged exposition instead of serving stale series forever.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.telemetry import metrics as tm
from ray_tpu.util import tracing
from ray_tpu.utils import metrics as instruments
from ray_tpu.utils.metrics_exporter import _fmt_tags

# pubsub channel the exporters publish snapshots on, and the durable
# per-host key late joiners / the report CLI read
CH_FLEETVIEW = "fleetview/host"
# mirrors fleet.coordinator.CH_BARRIER (defined there next to the
# publisher; duplicated literally to keep this module import-light)
CH_BARRIER = "fleet/barrier_arrival"
# the aggregator's own periodically-written digest, for
# ``python -m ray_tpu.telemetry.fleet_report`` against a live KV
K_AGGREGATE = "fleetview/aggregate"

INTERVAL_ENV = "RAY_TPU_FLEETVIEW_INTERVAL_S"
MAX_AGE_ENV = "RAY_TPU_FLEETVIEW_MAX_AGE_S"

# families the aggregator computes itself (rendered from its local
# registry, skipped in host snapshots so a coordinator that also runs
# an exporter can't duplicate them)
AGGREGATOR_FAMILIES = (
    tm.FLEET_BARRIER_WAIT_SECONDS,
    tm.FLEET_STRAGGLER_TOTAL,
    tm.FLEET_HOSTS_REPORTING,
)


def snapshot_key(host: str) -> str:
    return f"fleetview/host/{host}"


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# -- collective drain-point arrivals (put_global, resize) --------------
#
# Hot paths call record_arrival(); it is one flag check until a
# HostExporter arms it. Under the lockstep contract every host reaches
# the k-th arrival of a named point together, so (point, index) is a
# cross-host join key the aggregator can attribute without barriers.

_ARR_ON = False
_ARR_LOCK = threading.Lock()
_ARR_RECORDS: "collections.deque" = collections.deque(maxlen=512)
_ARR_COUNTS: Dict[str, int] = {}


def arrivals_on() -> bool:
    return _ARR_ON


def record_arrival(point: str, ts: Optional[float] = None) -> None:
    """Record this process's arrival at a collective drain point
    (``put_global`` placement, a resize). No-op (one flag check) when
    no exporter runs."""
    if not _ARR_ON:
        return
    if ts is None:
        ts = time.time()
    with _ARR_LOCK:
        idx = _ARR_COUNTS.get(point, 0)
        _ARR_COUNTS[point] = idx + 1
        _ARR_RECORDS.append(
            {"point": point, "index": idx, "ts": ts}
        )


def _drain_arrivals() -> List[Dict[str, Any]]:
    with _ARR_LOCK:
        out = list(_ARR_RECORDS)
        _ARR_RECORDS.clear()
    return out


def _reset_arrivals() -> None:
    with _ARR_LOCK:
        _ARR_RECORDS.clear()
        _ARR_COUNTS.clear()


# -- snapshot building --------------------------------------------------


def registry_snapshot() -> List[Dict[str, Any]]:
    """Serialize the local metric registry: one dict per family
    (name / kind / description / boundaries for histograms / series as
    ``(sorted-tag-items, value)`` pairs), families sorted by name so a
    snapshot renders byte-stable."""
    fams: List[Dict[str, Any]] = []
    for m in instruments.all_metrics():
        fam: Dict[str, Any] = {
            "name": m.name,
            "kind": m.kind,
            "description": m.description,
        }
        if isinstance(m, instruments.Histogram):
            fam["boundaries"] = list(m.boundaries)
            fam["series"] = [
                (list(tags), dict(val)) for tags, val in m.series()
            ]
        else:
            fam["series"] = [
                (list(tags), val) for tags, val in m.series()
            ]
        fams.append(fam)
    fams.sort(key=lambda f: f["name"])
    return fams


def clock_handshake(kv, samples: int = 3) -> Tuple[float, float]:
    """NTP-style skew measurement against the KV server's clock.
    Returns ``(offset_s, rtt_s)`` from the minimum-RTT sample, where
    ``offset = host_clock − kv_clock`` (positive = this host runs
    ahead): the server stamp is assumed taken at the midpoint of the
    round trip, so the offset error is bounded by rtt/2."""
    best: Optional[Tuple[float, float]] = None
    for _ in range(max(1, samples)):
        t0 = time.time()
        ts = kv.server_clock()
        t1 = time.time()
        rtt = t1 - t0
        off = (t0 + t1) / 2.0 - ts
        if best is None or rtt < best[1]:
            best = (off, rtt)
    return best


class HostExporter:
    """One per host: periodically publish this process's observability
    snapshot onto the fleet KV plane.

    Each tick measures clock skew (:func:`clock_handshake`), then
    publishes {metrics registry, device-ledger digest, span segments
    finished since the last tick, drained collective-arrival records}
    on :data:`CH_FLEETVIEW` *and* writes it to
    ``fleetview/host/<host>`` (so late-joining aggregators and the
    report CLI see the latest state without a subscription).

    ``interval <= 0`` runs no thread — callers drive :meth:`flush`
    (tests, the bench harness)."""

    def __init__(
        self,
        kv,
        host: str,
        interval: Optional[float] = None,
        max_spans_per_tick: int = 2000,
    ):
        global _ARR_ON
        self.kv = kv
        self.host = host
        self.interval = (
            interval
            if interval is not None
            else _env_f(INTERVAL_ENV, 2.0)
        )
        self.seq = 0
        self.clock_offset_s = 0.0
        self.rtt_s = 0.0
        self.kv_failures = 0
        self.kv_reconnects = 0
        self._kv_degraded = False
        self._span_watermark = 0.0
        self._max_spans = int(max_spans_per_tick)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _ARR_ON = True  # arm the drain-point recorder
        if self.interval > 0:
            self._thread = threading.Thread(
                target=self._run,
                name="fleetview-exporter",
                daemon=True,
            )
            self._thread.start()

    # ray-tpu: thread=fleetview-exporter
    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.flush()
            except Exception:
                pass  # KV hiccups must not kill the exporter

    def flush(self) -> Dict[str, Any]:
        """One tick: handshake, snapshot, publish + put. Returns the
        snapshot (tests/bench call this directly for determinism).

        Outage-tolerant: a KV that is down past the transport's retry
        schedule costs this tick its put/publish (counted in
        ``kv_failures``), never the exporter — the snapshot still
        returns, the next tick re-tries, and the first tick after an
        outage counts into ``ray_tpu_kv_reconnects_total{host}``."""
        try:
            off, rtt = clock_handshake(
                self.kv, samples=3 if self.seq == 0 else 1
            )
            self.clock_offset_s, self.rtt_s = off, rtt
            tm.set_clock_offset(self.host, off)
        except Exception:
            pass
        snap = self.snapshot()
        try:
            self.kv.put(snapshot_key(self.host), snap)
            if self._kv_degraded:
                self._kv_degraded = False
                self.kv_reconnects += 1
                try:
                    tm.inc_kv_reconnects(self.host)
                except Exception:
                    pass
        except Exception:
            self.kv_failures += 1
            self._kv_degraded = True
        try:
            self.kv.publish(CH_FLEETVIEW, snap)
        except Exception:
            pass
        self.seq += 1
        return snap

    def snapshot(self) -> Dict[str, Any]:
        """Assemble (without publishing) this host's snapshot."""
        spans: List[Dict[str, Any]] = []
        if tracing.is_enabled():
            wm = self._span_watermark
            for s in tracing.get_spans():
                end = s.get("end") or s.get("start") or 0.0
                if end > wm:
                    spans.append(s)
            if spans:
                self._span_watermark = max(
                    (s.get("end") or s.get("start") or 0.0)
                    for s in spans
                )
                spans = spans[-self._max_spans :]
        ledger = None
        try:
            from ray_tpu.telemetry import device

            if device.enabled():
                full = device.snapshot()
                ledger = {
                    "totals": full.get("totals"),
                    "peak_flops_per_device": full.get(
                        "peak_flops_per_device"
                    ),
                    "programs": [
                        {
                            k: p.get(k)
                            for k in (
                                "label",
                                "executions",
                                "flops",
                                "mfu",
                                "device_time_s",
                            )
                        }
                        for p in full.get("programs", ())
                    ],
                }
        except Exception:
            ledger = None
        return {
            "host": self.host,
            "seq": self.seq,
            "ts": time.time(),
            "clock_offset_s": self.clock_offset_s,
            "rtt_s": self.rtt_s,
            "metrics": registry_snapshot(),
            "spans": spans,
            "arrivals": _drain_arrivals(),
            "ledger": ledger,
        }

    def stop(self) -> None:
        global _ARR_ON
        _ARR_ON = False
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1.0)
            self._thread = None


# -- the aggregator -----------------------------------------------------


def _merge_value(kind: str, prev, new):
    """Cross-host merge on a full-key collision (same family, same
    complete tag set after host injection): counters SUM (each host
    counted its own events), gauges LAST-WRITE in sorted host order
    (a point-in-time reading has no meaningful sum), histograms merge
    bucket-wise."""
    if kind == "counter":
        return prev + new
    if kind == "histogram" and isinstance(prev, dict):
        pb, nb = prev.get("buckets", []), new.get("buckets", [])
        if len(pb) != len(nb):
            return new
        return {
            "buckets": [a + b for a, b in zip(pb, nb)],
            "sum": prev.get("sum", 0.0) + new.get("sum", 0.0),
            "count": prev.get("count", 0) + new.get("count", 0),
        }
    return new  # gauge (and unknown kinds): last write wins


class FleetAggregator:
    """The coordinator-side half: merge every host's published
    snapshot into one exposition / one timeline / per-host barrier
    attribution.

    Runs a :class:`~ray_tpu.fleet.kv.Subscriber` on the fleetview and
    barrier-arrival channels; the callback only ingests (pure compute
    + local metric writes under one lock — never a KV round trip with
    the lock held). :meth:`ingest` / :meth:`ingest_barrier` are also
    public so tests and offline tools can feed snapshots directly.

    Staleness: a host whose last snapshot is older than ``max_age``
    is pruned at render time — its series age out of the merged
    exposition instead of lingering forever after the host left."""

    def __init__(
        self,
        kv=None,
        max_age: Optional[float] = None,
        subscribe: bool = True,
        publish_aggregate: bool = True,
        max_spans_per_host: int = 20000,
        poll_timeout: float = 1.0,
    ):
        self.kv = kv
        self.max_age = (
            max_age
            if max_age is not None
            else _env_f(MAX_AGE_ENV, 15.0)
        )
        self.publish_aggregate = publish_aggregate and kv is not None
        self.max_spans_per_host = int(max_spans_per_host)
        self._lock = threading.Lock()
        self._snaps: Dict[str, Dict[str, Any]] = {}
        self._spans: Dict[str, "collections.deque"] = {}
        self._arrivals: Dict[str, Dict[Tuple[str, int], float]] = {}
        self._collective_done: set = set()
        self._barriers: Dict[Tuple[int, str], Dict[str, float]] = {}
        self._barrier_world: Dict[Tuple[int, str], Tuple[str, ...]] = {}
        self._barrier_done: set = set()
        self.barrier_history: List[Dict[str, Any]] = []
        self.latest_gen = 0
        self._last_aggregate_put = 0.0
        self._sub = None
        if subscribe and kv is not None:
            from ray_tpu.fleet.kv import Subscriber

            self._sub = Subscriber(
                kv,
                [CH_FLEETVIEW, CH_BARRIER],
                self._on_message,
                poll_timeout=poll_timeout,
            )

    # ray-tpu: thread=fleetview-sub
    def _on_message(self, channel: str, msg: Dict[str, Any]) -> None:
        if channel == CH_BARRIER:
            self.ingest_barrier(msg)
            return
        self.ingest(msg)
        # refresh the durable digest for the report CLI (outside the
        # lock — RTA008: never hold a lock across a KV round trip),
        # throttled to one put per second
        if self.publish_aggregate:
            now = time.monotonic()
            if now - self._last_aggregate_put >= 1.0:
                self._last_aggregate_put = now
                try:
                    self.kv.put(K_AGGREGATE, self.report_data())
                except Exception:
                    pass

    def ingest(self, snap: Dict[str, Any]) -> None:
        """Absorb one host snapshot (pubsub callback or direct)."""
        host = snap.get("host")
        if not host:
            return
        now = time.time()
        with self._lock:
            self._snaps[host] = dict(snap, _recv_at=now)
            dq = self._spans.get(host)
            if dq is None:
                dq = self._spans[host] = collections.deque(
                    maxlen=self.max_spans_per_host
                )
            dq.extend(snap.get("spans") or ())
            arr = self._arrivals.setdefault(host, {})
            for rec in snap.get("arrivals") or ():
                try:
                    arr[(str(rec["point"]), int(rec["index"]))] = (
                        float(rec["ts"])
                    )
                except (KeyError, TypeError, ValueError):
                    continue
            self._attribute_collectives_locked()

    def ingest_barrier(self, rec: Dict[str, Any]) -> None:
        """Absorb one barrier-arrival event (HostAgent.barrier's
        CH_BARRIER publish). When every host of the record's epoch has
        arrived, attribute waits + the straggler."""
        try:
            gen = int(rec["gen"])
            name = str(rec["name"])
            host = str(rec["host"])
            ts = float(rec["ts"])
        except (KeyError, TypeError, ValueError):
            return
        with self._lock:
            self.latest_gen = max(self.latest_gen, gen)
            key = (gen, name)
            if key in self._barrier_done:
                return
            world = tuple(rec.get("hosts") or ())
            if world:
                self._barrier_world[key] = world
            self._barriers.setdefault(key, {})[host] = ts
            world = self._barrier_world.get(key, ())
            arr = self._barriers[key]
            if world and set(world) <= set(arr):
                self._attribute_locked(
                    gen,
                    name,
                    {h: arr[h] for h in world},
                    kind="barrier",
                )
                self._barrier_done.add(key)
                self._barriers.pop(key, None)

    # -- attribution (under self._lock; local compute only) ------------

    def _offset_locked(self, host: str) -> float:
        snap = self._snaps.get(host)
        if snap is None:
            return 0.0
        try:
            return float(snap.get("clock_offset_s") or 0.0)
        except (TypeError, ValueError):
            return 0.0

    def _attribute_collectives_locked(self) -> None:
        """Attribute every (point, index) drain point all live hosts
        have reached — the lockstep contract makes the pair a
        cross-host join key without any barrier."""
        live = sorted(self._snaps)
        if len(live) < 2:
            return
        for key in list(self._arrivals.get(live[0], {})):
            if key in self._collective_done:
                continue
            if not all(
                key in self._arrivals.get(h, {}) for h in live
            ):
                continue
            arrivals = {h: self._arrivals[h][key] for h in live}
            self._attribute_locked(
                self.latest_gen,
                f"{key[0]}[{key[1]}]",
                arrivals,
                kind="collective",
            )
            self._collective_done.add(key)
            if len(self._collective_done) > 8192:
                self._collective_done.clear()
            for h in live:
                self._arrivals.get(h, {}).pop(key, None)

    def _attribute_locked(
        self,
        gen: int,
        name: str,
        arrivals: Dict[str, float],
        kind: str,
    ) -> None:
        corrected = {
            h: arrivals[h] - self._offset_locked(h)
            for h in sorted(arrivals)
        }
        t_last = max(corrected.values())
        straggler = max(
            sorted(corrected), key=lambda h: corrected[h]
        )
        waits = {h: t_last - t for h, t in corrected.items()}
        for h, w in waits.items():
            tm.set_barrier_wait(h, gen, w)
        tm.inc_straggler(straggler)
        rec = {
            "gen": gen,
            "name": name,
            "kind": kind,
            "straggler": straggler,
            "start": min(corrected.values()),
            "end": t_last,
            "waits": waits,
        }
        self.barrier_history.append(rec)
        if len(self.barrier_history) > 1024:
            del self.barrier_history[
                : len(self.barrier_history) - 1024
            ]
        # the fleet-level span, in the KV clock frame already
        tracing.record_span(
            "fleet:barrier",
            rec["start"],
            rec["end"],
            barrier=name,
            gen=gen,
            straggler=straggler,
            kind=kind,
        )

    # -- reads ----------------------------------------------------------

    def _prune_locked(self, now: float) -> None:
        for host in [
            h
            for h, s in self._snaps.items()
            if now - s.get("_recv_at", now) > self.max_age
        ]:
            del self._snaps[host]

    def hosts(self) -> List[str]:
        """Hosts with a live (non-aged) snapshot, sorted."""
        with self._lock:
            self._prune_locked(time.time())
            return sorted(self._snaps)

    def merged_exposition(self) -> str:
        """The fleet's ONE Prometheus exposition: every live host's
        families with a ``host=`` label injected on series that lack
        one, plus the aggregator-computed families (barrier waits /
        stragglers / hosts-reporting) from the local registry. Family
        order is sorted by name; within a family, series iterate hosts
        in sorted order — byte-stable across scrapes given the same
        snapshots (the golden-test contract)."""
        with self._lock:
            self._prune_locked(time.time())
            snaps = [self._snaps[h] for h in sorted(self._snaps)]
        tm.set_hosts_reporting(len(snaps))
        fams: Dict[str, Dict[str, Any]] = {}

        def add_family(fam, inject_host=None):
            name = fam.get("name")
            if not name:
                return
            rec = fams.get(name)
            if rec is None:
                rec = fams[name] = {
                    "kind": fam.get("kind", "untyped"),
                    "description": fam.get("description", ""),
                    "boundaries": fam.get("boundaries"),
                    "series": collections.OrderedDict(),
                }
            for tags, value in fam.get("series", ()):
                t = dict(tags)
                if inject_host is not None and "host" not in t:
                    t["host"] = inject_host
                key = tuple(sorted(t.items()))
                prev = rec["series"].get(key)
                if prev is None:
                    rec["series"][key] = value
                else:
                    rec["series"][key] = _merge_value(
                        rec["kind"], prev, value
                    )

        local = {f["name"]: f for f in registry_snapshot()}
        for name in AGGREGATOR_FAMILIES:
            if name in local:
                add_family(local[name])
        for snap in snaps:
            for fam in snap.get("metrics", ()):
                if fam.get("name") in AGGREGATOR_FAMILIES:
                    continue
                add_family(fam, inject_host=snap["host"])
        lines: List[str] = []
        for name in sorted(fams):
            rec = fams[name]
            pname = name.replace(".", "_")
            if rec["description"]:
                lines.append(
                    f"# HELP {pname} {rec['description']}"
                )
            lines.append(f"# TYPE {pname} {rec['kind']}")
            if rec["kind"] == "histogram":
                bounds = rec.get("boundaries") or []
                for key, data in rec["series"].items():
                    cum = 0.0
                    for b, c in zip(bounds, data["buckets"]):
                        cum += c
                        t = dict(key)
                        t["le"] = repr(float(b))
                        lines.append(
                            f"{pname}_bucket"
                            f"{_fmt_tags(sorted(t.items()))} {cum}"
                        )
                    total = sum(data["buckets"])
                    t = dict(key)
                    t["le"] = "+Inf"
                    lines.append(
                        f"{pname}_bucket"
                        f"{_fmt_tags(sorted(t.items()))} {total}"
                    )
                    lines.append(
                        f"{pname}_sum{_fmt_tags(key)} {data['sum']}"
                    )
                    lines.append(
                        f"{pname}_count{_fmt_tags(key)}"
                        f" {data['count']}"
                    )
            else:
                for key, value in rec["series"].items():
                    lines.append(
                        f"{pname}{_fmt_tags(key)} {value}"
                    )
        return "\n".join(lines) + "\n"

    def export_fleet_timeline(
        self, path: str, since: Optional[float] = None
    ) -> str:
        """One chrome://tracing file for the whole fleet: each host's
        shipped spans shifted into the KV clock frame (``t − offset``),
        the per-host child-clamp rule of
        :func:`tracing._clamped_intervals` reused, one synthetic
        process-lane group per (host, original pid) labeled with the
        host name — device lanes ride along because the PR-13 ledger
        records its ``device:`` spans into the same buffer the
        exporter ships. Attributed barriers render on a ``fleet`` lane
        (pid 0) naming the straggler."""
        with self._lock:
            hosts = sorted(set(self._spans) | set(self._snaps))
            per_host = {
                h: list(self._spans.get(h, ())) for h in hosts
            }
            offsets = {h: self._offset_locked(h) for h in hosts}
            barriers = list(self.barrier_history)
        events: List[Dict[str, Any]] = []
        pid_map: Dict[Tuple[str, int], int] = {}

        def lane_pid(host, orig_pid):
            key = (host, orig_pid)
            if key not in pid_map:
                pid_map[key] = len(pid_map) + 1
                events.append(
                    {
                        "name": "process_name",
                        "ph": "M",
                        "pid": pid_map[key],
                        "tid": 0,
                        "args": {
                            "name": f"{host} (pid {orig_pid})"
                        },
                    }
                )
            return pid_map[key]

        for host in hosts:
            spans = per_host[host]
            if since is not None:
                spans = [
                    s
                    for s in spans
                    if (s.get("end") or s.get("start") or 0.0)
                    >= since
                ]
            off = offsets.get(host, 0.0)
            shifted = []
            for s in spans:
                c = dict(s)
                c["start"] = s["start"] - off
                c["end"] = (
                    s["end"]
                    if s.get("end") is not None
                    else s["start"]
                ) - off
                shifted.append(c)
            clamped = tracing._clamped_intervals(shifted)
            lanes: Dict[Tuple[int, int], Optional[str]] = {}
            for s in shifted:
                start, end = clamped.get(
                    s.get("span_id"), (s["start"], s["end"])
                )
                pid = lane_pid(host, s.get("pid", 0))
                tid = s.get("tid", 0)
                events.append(
                    {
                        "name": s["name"],
                        "cat": "span",
                        "ph": "X",
                        "ts": start * 1e6,
                        "dur": (end - start) * 1e6,
                        "pid": pid,
                        "tid": tid,
                        "args": {
                            "host": host,
                            "trace_id": s.get("trace_id"),
                            "span_id": s.get("span_id"),
                            "parent_id": s.get("parent_id"),
                            **(s.get("attributes") or {}),
                        },
                    }
                )
                lanes.setdefault(
                    (pid, tid), s.get("thread_name")
                )
            for (pid, tid), tname in sorted(lanes.items()):
                if tname:
                    events.append(
                        {
                            "name": "thread_name",
                            "ph": "M",
                            "pid": pid,
                            "tid": tid,
                            "args": {"name": tname},
                        }
                    )
        if barriers:
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": 0,
                    "tid": 0,
                    "args": {"name": "fleet"},
                }
            )
            for rec in barriers:
                if since is not None and rec["end"] < since:
                    continue
                events.append(
                    {
                        "name": "fleet:barrier",
                        "cat": "span",
                        "ph": "X",
                        "ts": rec["start"] * 1e6,
                        "dur": max(0.0, rec["end"] - rec["start"])
                        * 1e6,
                        "pid": 0,
                        "tid": 0,
                        "args": {
                            "barrier": rec["name"],
                            "gen": rec["gen"],
                            "kind": rec["kind"],
                            "straggler": rec["straggler"],
                            "waits": rec["waits"],
                        },
                    }
                )
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        return path

    def report_data(self) -> Dict[str, Any]:
        """JSON-safe digest for the report CLI / the KV aggregate key:
        per-host health (age, seq, skew, rtt, ledger MFU), barrier
        history, latest epoch generation."""
        now = time.time()
        with self._lock:
            self._prune_locked(now)
            hosts = []
            for h in sorted(self._snaps):
                s = self._snaps[h]
                ledger = s.get("ledger") or {}
                totals = ledger.get("totals") or {}
                hosts.append(
                    {
                        "host": h,
                        "seq": s.get("seq"),
                        "age_s": now - s.get("_recv_at", now),
                        "clock_offset_s": s.get("clock_offset_s"),
                        "rtt_s": s.get("rtt_s"),
                        "mfu": totals.get("mfu"),
                        "kv_rtt_s": _family_value(
                            s, tm.KV_RTT_SECONDS
                        ),
                        "spans_buffered": len(
                            self._spans.get(h, ())
                        ),
                    }
                )
            return {
                "ts": now,
                "max_age_s": self.max_age,
                "latest_gen": self.latest_gen,
                "hosts": hosts,
                "barriers": list(self.barrier_history[-50:]),
            }

    def stop(self) -> None:
        if self._sub is not None:
            self._sub.stop()
            self._sub = None


def _family_value(snap: Dict[str, Any], family: str):
    """First series value of ``family`` in a snapshot's serialized
    registry (None when the host never set it)."""
    for fam in snap.get("metrics", ()):
        if fam.get("name") == family:
            for _tags, value in fam.get("series", ()):
                return value
    return None


# -- process-wide installation (the ingress /metrics hook) -------------

_INSTALLED: Optional[FleetAggregator] = None


def install(agg: FleetAggregator) -> FleetAggregator:
    """Make ``agg`` this process's fleet view: the ingress ``/metrics``
    route and any MetricsServer constructed with
    ``render=fleetview.render_installed`` serve its merged exposition
    instead of the process-local one."""
    global _INSTALLED
    _INSTALLED = agg
    return agg


def current() -> Optional[FleetAggregator]:
    return _INSTALLED


def uninstall(agg: Optional[FleetAggregator] = None) -> None:
    global _INSTALLED
    if agg is None or _INSTALLED is agg:
        _INSTALLED = None


def render_installed() -> Optional[str]:
    """Merged exposition of the installed aggregator, or None (callers
    fall back to the process-local exposition)."""
    agg = _INSTALLED
    if agg is None:
        return None
    return agg.merged_exposition()
