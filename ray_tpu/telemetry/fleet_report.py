"""Fleet report: render the merged fleet-observability snapshot.

::

    python -m ray_tpu.telemetry.fleet_report --kv HOST:PORT [--json]
    python -m ray_tpu.telemetry.fleet_report --dump aggregate.json

Two sources:

- ``--kv`` connects to a live fleet KV server. It prefers the
  aggregator's periodically-written digest (``fleetview/aggregate``,
  refreshed by a running :class:`~ray_tpu.telemetry.fleetview
  .FleetAggregator`), which carries barrier walls and straggler
  attribution; when no aggregator is running it falls back to reading
  the fleet member list and each host's ``fleetview/host/<host>``
  snapshot directly (health + skew + MFU, no barrier history).
- ``--dump`` renders a JSON file previously written from
  :meth:`FleetAggregator.report_data` (post-mortem).

Sections: per-host health (snapshot age vs the staleness horizon, seq,
clock offset, KV RTT, ledger MFU), barrier/collective walls (who the
last arriver was, how long everyone else stood waiting), and the
epoch history read from the coordinator's immutable epoch records.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def _snapshot_to_host_row(
    host: str, snap: Dict[str, Any]
) -> Dict[str, Any]:
    """Shape a raw fleetview/host/<host> snapshot like one row of
    FleetAggregator.report_data()['hosts'] (age unknown: the KV has no
    receive stamp, so we report the sender's own publish time)."""
    from ray_tpu.telemetry import fleetview, metrics as tm

    ledger = snap.get("ledger") or {}
    totals = ledger.get("totals") or {}
    return {
        "host": host,
        "seq": snap.get("seq"),
        "age_s": None,
        "publish_ts": snap.get("ts"),
        "clock_offset_s": snap.get("clock_offset_s"),
        "rtt_s": snap.get("rtt_s"),
        "mfu": totals.get("mfu"),
        "kv_rtt_s": fleetview._family_value(
            snap, tm.KV_RTT_SECONDS
        ),
        "spans_buffered": len(snap.get("spans") or ()),
    }


def _epoch_history(client, max_epochs: int = 20) -> List[Dict]:
    """Walk the coordinator's immutable epoch records back from the
    latest generation pointer."""
    from ray_tpu.fleet.coordinator import K_EPOCH_PTR, epoch_key

    out: List[Dict] = []
    try:
        gen = client.get(K_EPOCH_PTR, timeout=2.0)
    except KeyError:
        return out
    if not gen:
        return out
    lo = max(1, int(gen) - max_epochs + 1)
    for g in range(int(gen), lo - 1, -1):
        try:
            rec = client.get(epoch_key(g), timeout=2.0)
        except KeyError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def build_report(
    kv: Optional[str] = None,
    dump: Optional[str] = None,
    token: Optional[str] = None,
) -> Dict[str, Any]:
    if dump:
        with open(dump) as f:
            report = json.load(f)
        report.setdefault("source", f"dump:{dump}")
        return report
    if not kv:
        raise ValueError("need --kv HOST:PORT or --dump FILE")
    from ray_tpu.fleet.coordinator import K_MEMBERS
    from ray_tpu.fleet.kv import KVClient
    from ray_tpu.telemetry import fleetview

    address = kv if ":" in kv else f"127.0.0.1:{kv}"
    client = KVClient(address, token=token)
    try:
        agg = client.get(fleetview.K_AGGREGATE, timeout=2.0)
    except KeyError:
        agg = None
    if isinstance(agg, dict) and agg.get("hosts") is not None:
        report = dict(agg, source=f"kv:{kv} (aggregator)")
    else:
        # no aggregator running: read host snapshots directly
        try:
            members = client.get(K_MEMBERS, timeout=2.0) or {}
        except KeyError:
            members = {}
        hosts = []
        for h in sorted(members):
            try:
                snap = client.get(
                    fleetview.snapshot_key(h), timeout=2.0
                )
            except KeyError:
                continue
            if isinstance(snap, dict):
                hosts.append(_snapshot_to_host_row(h, snap))
        report = {
            "source": f"kv:{kv} (direct, no aggregator)",
            "hosts": hosts,
            "barriers": [],
        }
    report["epochs"] = _epoch_history(client)
    if report.get("latest_gen") is None and report["epochs"]:
        report["latest_gen"] = report["epochs"][0].get("gen")
    return report


def _ms(v) -> str:
    return "-" if v is None else f"{1e3 * float(v):.2f}"


def render_text(report: Dict[str, Any]) -> str:
    out: List[str] = []
    hosts = report.get("hosts") or []
    out.append(
        f"== fleet view: {report.get('source', '?')} "
        f"({len(hosts)} hosts reporting, "
        f"gen {report.get('latest_gen', '-')}) =="
    )
    out.append("")
    out.append("-- hosts --")
    out.append(
        f"{'host':20s} {'seq':>5s} {'health':>7s} {'age_s':>7s} "
        f"{'offset_ms':>10s} {'kv_rtt_ms':>10s} {'mfu%':>6s} "
        f"{'spans':>7s}"
    )
    max_age = report.get("max_age_s")
    for h in hosts:
        age = h.get("age_s")
        if age is None:
            health = "?"
            age_s = "-"
        else:
            stale = max_age is not None and age > max_age
            health = "STALE" if stale else "live"
            age_s = f"{age:.1f}"
        mfu = h.get("mfu")
        kv_rtt = h.get("kv_rtt_s")
        if kv_rtt is None:
            kv_rtt = h.get("rtt_s")
        out.append(
            f"{str(h.get('host'))[:20]:20s} "
            f"{str(h.get('seq', '-')):>5s} {health:>7s} "
            f"{age_s:>7s} {_ms(h.get('clock_offset_s')):>10s} "
            f"{_ms(kv_rtt):>10s} "
            f"{(f'{100 * mfu:.2f}' if mfu else '-'):>6s} "
            f"{str(h.get('spans_buffered', '-')):>7s}"
        )
    barriers = report.get("barriers") or []
    out.append("")
    out.append(f"-- barrier walls ({len(barriers)}) --")
    if barriers:
        out.append(
            f"{'gen':>4s} {'barrier':28s} {'kind':>10s} "
            f"{'straggler':20s} {'max_wait_ms':>12s}"
        )
    for b in barriers[-20:]:
        waits = b.get("waits") or {}
        max_wait = max(waits.values()) if waits else None
        out.append(
            f"{str(b.get('gen', '-')):>4s} "
            f"{str(b.get('name'))[:28]:28s} "
            f"{str(b.get('kind', '-')):>10s} "
            f"{str(b.get('straggler'))[:20]:20s} "
            f"{_ms(max_wait):>12s}"
        )
    epochs = report.get("epochs") or []
    out.append("")
    out.append(f"-- epoch history ({len(epochs)}) --")
    for e in epochs:
        hosts_e = e.get("hosts") or ()
        out.append(
            f"gen {e.get('gen')}: {len(hosts_e)} hosts "
            f"({', '.join(str(h) for h in hosts_e)})"
        )
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.telemetry.fleet_report",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument(
        "--kv", help="live fleet KV endpoint, HOST:PORT"
    )
    ap.add_argument(
        "--dump",
        help="FleetAggregator.report_data() JSON (post-mortem)",
    )
    ap.add_argument(
        "--token",
        help="KV auth token (default: RAY_TPU_KV_TOKEN env)",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit JSON, not text"
    )
    args = ap.parse_args(argv)
    if not args.kv and not args.dump:
        ap.error("one of --kv or --dump is required")
    report = build_report(
        kv=args.kv, dump=args.dump, token=args.token
    )
    if args.json:
        print(json.dumps(report, indent=1, default=str))
    else:
        print(render_text(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
