"""Device-plane observability: the compiled-program ledger.

PR 3's telemetry instruments the *host* hot path; by now the actual
work lives in opaque device programs — a ``train:learn_on_batch`` span
covers a K-update superstep, fused rollout never surfaces per-program
cost, and ``jit:recompile`` says *that* a retrace happened, not *why*.
This module is the device-side counterpart: a process-wide ledger,
hooked into the ``sharding/compile.sharded_jit`` cache, that records
per compiled program

- identity: label, donation flags, in/out shardings, creation time;
- compile cost: wall time per trace, abstract signatures;
- program cost (``Lowered.compile()`` substrate, the AOT machinery of
  SNIPPETS [1]): ``cost_analysis()`` FLOPs and bytes accessed,
  ``memory_analysis()`` HBM footprint (argument/output/temp/alias
  bytes);
- runtime: execution count and cumulative device-busy wall time,
  closed out at the policy drain points (the RTA005-annotated ONE
  counted drain per superstep) so async dispatch doesn't under-report;
- **recompile forensics**: on a trace beyond the first, the new
  abstract signature is diffed against the cached ones and the
  differing leaf path / shape / dtype rides the ``jit:recompile``
  event and the ``compile_stats()["recompile_causes"]`` rollup;
- **MFU / bandwidth accounting** against a per-device-kind peak-FLOPs
  table (``RAY_TPU_PEAK_FLOPS`` / ``telemetry(peak_flops=...)``
  override it, so the CPU container reports meaningful numbers).

Execution spans land in the trace buffer on synthetic ``device:`` +
program lanes, so ``Algorithm.export_timeline`` renders driver
threads, worker spans, and device programs in ONE perfetto file.

The ledger is off by default (one flag check per dispatch). The
telemetry runtime enables it (``AlgorithmConfig.telemetry(...)``), or
``RAY_TPU_DEVICE_LEDGER=1`` does with no config at all. The cost /
memory analysis pays one extra ahead-of-time compile per traced
signature (the jit execution cache and the AOT cache are disjoint);
``device_ledger="light"`` keeps the counters and forensics without it.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util import tracing

# -- activation ---------------------------------------------------------

_LOCK = threading.Lock()
_enabled = os.environ.get("RAY_TPU_DEVICE_LEDGER") == "1"
# capture cost/memory analysis (one extra AOT compile per signature)
_analyze = os.environ.get("RAY_TPU_DEVICE_LEDGER_LIGHT") != "1"

# label -> _ProgramEntry, insertion-ordered (dict is)
_entries: Dict[str, "_ProgramEntry"] = {}
# thread id -> [(entry, t_wall0, t_wall_ret)] dispatches not yet
# closed by a drain point (flushed lazily — see drain_point)
_pending: Dict[int, List[Tuple["_ProgramEntry", float, float]]] = {}

# synthetic chrome-trace lane block for device program spans: far away
# from any real thread id, one sub-lane per program label
_DEVICE_TID_BASE = 0x0DE00000
_span_seq = itertools.count()


def enable(analyze: Optional[bool] = None) -> None:
    global _enabled, _analyze
    _enabled = True
    if analyze is not None:
        _analyze = bool(analyze)


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def analyzing() -> bool:
    return _enabled and _analyze


def clear() -> None:
    """Drop all ledger state (tests)."""
    with _LOCK:
        _entries.clear()
        _pending.clear()


# -- peak-FLOPs / peak-bandwidth tables ---------------------------------

# per-chip peak FLOPs (bf16 where the chip has it) and peak HBM
# bytes/s, keyed by device_kind substring (public specs). The CPU
# entry is a placeholder a container overrides — MFU against a wrong
# peak is still a useful *relative* number across programs.
PEAK_FLOPS_TABLE: Tuple[Tuple[str, float], ...] = (
    ("v6", 918e12),      # v6e (Trillium)
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("cpu", 5e10),
)
PEAK_HBM_TABLE: Tuple[Tuple[str, float], ...] = (
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5 lite", 819e9),
    ("v5e", 819e9),
    ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
    ("cpu", 20e9),
)

_peak_flops_override: Optional[float] = None
_peak_hbm_override: Optional[float] = None


def set_peak_flops(
    flops: Optional[float], hbm_bytes_per_s: Optional[float] = None
) -> None:
    """Override the per-device peak (``telemetry(peak_flops=...)``) —
    the CPU-container knob that makes container MFU meaningful."""
    global _peak_flops_override, _peak_hbm_override
    _peak_flops_override = float(flops) if flops else None
    if hbm_bytes_per_s is not None:
        _peak_hbm_override = float(hbm_bytes_per_s) or None


def device_kind() -> str:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def peak_flops_per_device(kind: Optional[str] = None) -> float:
    env = os.environ.get("RAY_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if _peak_flops_override:
        return _peak_flops_override
    k = (kind or device_kind()).lower()
    for key, peak in PEAK_FLOPS_TABLE:
        if key in k:
            return peak
    return PEAK_FLOPS_TABLE[-1][1]


def peak_hbm_bytes_per_s(kind: Optional[str] = None) -> float:
    env = os.environ.get("RAY_TPU_PEAK_HBM_BPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if _peak_hbm_override:
        return _peak_hbm_override
    k = (kind or device_kind()).lower()
    for key, peak in PEAK_HBM_TABLE:
        if key in k:
            return peak
    return PEAK_HBM_TABLE[-1][1]


# -- abstract signatures / forensics ------------------------------------


def _leaf_desc(x: Any) -> str:
    """Compact shape/dtype descriptor of one abstract leaf:
    ``f32[128,4]`` (jax's own notation)."""
    dtype = getattr(x, "dtype", None)
    shape = getattr(x, "shape", None)
    if dtype is None or shape is None:
        return f"py:{type(x).__name__}={x!r}"[:64]
    try:
        import jax

        short = jax.ShapeDtypeStruct(shape, dtype).str_short()
    except Exception:
        short = f"{dtype}[{','.join(str(d) for d in shape)}]"
    return short


def signature_of(args, kwargs, static_argnames=()) -> Tuple:
    """Abstract (path → shape/dtype) signature of one call, the unit
    the forensics diff operates on. Static kwargs compare by value."""
    import jax

    statics = {
        k: kwargs[k] for k in static_argnames if k in kwargs
    }
    dyn_kwargs = {
        k: v for k, v in kwargs.items() if k not in statics
    }
    leaves = []
    flat = jax.tree_util.tree_flatten_with_path(
        (args, dyn_kwargs)
    )[0]
    for path, leaf in flat:
        leaves.append(
            (jax.tree_util.keystr(path), _leaf_desc(leaf))
        )
    for k in sorted(statics):
        leaves.append((f"static:{k}", repr(statics[k])[:64]))
    return tuple(leaves)


def diff_signatures(old: Tuple, new: Tuple) -> Dict[str, Any]:
    """What changed between two abstract signatures: the leaf paths
    whose shape/dtype differ, plus added/removed paths. This IS the
    recompile cause — jit retraced because some leaf's abstract value
    (or the tree structure itself) moved."""
    a, b = dict(old), dict(new)
    changed = [
        {"path": p, "from": a[p], "to": b[p]}
        for p in a
        if p in b and a[p] != b[p]
    ]
    added = [{"path": p, "to": b[p]} for p in b if p not in a]
    removed = [{"path": p, "from": a[p]} for p in a if p not in b]
    out: Dict[str, Any] = {}
    if changed:
        out["changed"] = changed
    if added:
        out["added"] = added
    if removed:
        out["removed"] = removed
    return out


def cause_string(diff: Dict[str, Any], limit: int = 6) -> str:
    """One-line human rendering of a signature diff (what the
    ``jit:recompile`` event carries)."""
    parts = []
    for c in diff.get("changed", ())[:limit]:
        parts.append(f"{c['path']}: {c['from']} -> {c['to']}")
    for c in diff.get("added", ())[:limit]:
        parts.append(f"+{c['path']}: {c['to']}")
    for c in diff.get("removed", ())[:limit]:
        parts.append(f"-{c['path']}: {c['from']}")
    n = sum(len(diff.get(k, ())) for k in ("changed", "added", "removed"))
    if n > limit:
        parts.append(f"(+{n - limit} more)")
    return "; ".join(parts) if parts else "identical abstract signature (static/config retrace)"


# -- the ledger ---------------------------------------------------------


class _ProgramEntry:
    """One compiled program's ledger row."""

    __slots__ = (
        "label",
        "created",
        "donate_argnums",
        "in_shardings",
        "out_shardings",
        "traces",
        "compile_time_s",
        "executions",
        "device_time_s",
        "signatures",
        "causes",
        "flops",
        "bytes_accessed",
        "memory",
        "n_devices",
        "tid",
        "source",
    )

    def __init__(self, label: str, donate_argnums=(), in_specs=None,
                 out_specs=None):
        self.label = label
        self.created = time.time()
        self.donate_argnums = tuple(donate_argnums or ())
        self.in_shardings = _spec_str(in_specs)
        self.out_shardings = _spec_str(out_specs)
        self.traces = 0
        self.compile_time_s = 0.0
        self.executions = 0
        self.device_time_s = 0.0
        self.signatures: List[Tuple] = []
        self.causes: List[Dict[str, Any]] = []
        # per-execution program cost (None until analyzed)
        self.flops: Optional[float] = None
        self.bytes_accessed: Optional[float] = None
        self.memory: Optional[Dict[str, float]] = None
        self.n_devices = 1
        # how this program's executable came to exist: "live" (jit
        # traced+compiled in this process), "aot_live" (compiled ahead
        # of time here, seeding the AOT cache), or "aot_cache"
        # (deserialized from the persistent cache — compile_s stays 0
        # and no trace/forensics ever fire, because no compile
        # happened in this process)
        self.source = "live"
        # stable synthetic chrome-trace lane for this program
        self.tid = _DEVICE_TID_BASE + (
            zlib.crc32(label.encode()) % 0x10000
        )

    def to_dict(self) -> Dict[str, Any]:
        peak = peak_flops_per_device()
        out: Dict[str, Any] = {
            "label": self.label,
            "traces": self.traces,
            "recompiles": max(0, self.traces - 1),
            "compile_time_s": round(self.compile_time_s, 6),
            "executions": self.executions,
            "device_time_s": round(self.device_time_s, 6),
            "donate_argnums": list(self.donate_argnums),
            "in_shardings": self.in_shardings,
            "out_shardings": self.out_shardings,
            "n_devices": self.n_devices,
            "source": self.source,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "memory": self.memory,
            "recompile_causes": [
                c["cause"] for c in self.causes
            ],
        }
        out["mfu"] = program_mfu(
            self.flops, self.executions, self.device_time_s,
            self.n_devices, peak,
        )
        out["bandwidth_util"] = program_bandwidth_util(
            self.bytes_accessed, self.executions,
            self.device_time_s, self.n_devices,
        )
        return out


def _spec_str(spec, limit: int = 800) -> Optional[str]:
    if spec is None:
        return None
    s = str(spec)
    return s if len(s) <= limit else s[: limit - 3] + "..."


def program_mfu(
    flops, executions, device_time_s, n_devices, peak=None
) -> Optional[float]:
    """Model-FLOPs utilization of one program: executed FLOPs over the
    peak the busy interval could have delivered. ``flops`` is the
    compiled module's per-execution cost (``cost_analysis``); peak is
    per device × the devices the program spans."""
    if not flops or not executions or device_time_s <= 0:
        return None
    peak = peak or peak_flops_per_device()
    return float(flops) * executions / (
        device_time_s * peak * max(1, n_devices)
    )


def program_bandwidth_util(
    bytes_accessed, executions, device_time_s, n_devices, peak=None
) -> Optional[float]:
    if not bytes_accessed or not executions or device_time_s <= 0:
        return None
    peak = peak or peak_hbm_bytes_per_s()
    return float(bytes_accessed) * executions / (
        device_time_s * peak * max(1, n_devices)
    )


def _entry_for(sf) -> "_ProgramEntry":
    e = _entries.get(sf.label)
    if e is None:
        e = _entries[sf.label] = _ProgramEntry(
            sf.label,
            donate_argnums=getattr(sf, "donate_argnums", ()),
            in_specs=getattr(sf, "in_specs", None),
            out_specs=getattr(sf, "out_specs", None),
        )
    return e


def _sharding_devices(x) -> Optional[int]:
    sh = getattr(x, "sharding", None)
    ds = getattr(sh, "device_set", None)
    return len(ds) if ds else None


def _abstractify(args, kwargs, static_argnames=()):
    """(args, kwargs) with every array leaf replaced by its
    ``ShapeDtypeStruct`` (sharding preserved for committed jax
    arrays): what the AOT ``lower()`` consumes — no data read, so
    donated/deleted buffers are fine."""
    import jax
    import numpy as np

    statics = set(static_argnames)

    def to_sds(x):
        if isinstance(x, jax.Array):
            try:
                return jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=x.sharding
                )
            except Exception:
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
        if isinstance(x, np.ndarray):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    sds_args = jax.tree_util.tree_map(to_sds, args)
    sds_kwargs = {
        k: (v if k in statics else jax.tree_util.tree_map(to_sds, v))
        for k, v in kwargs.items()
    }
    return sds_args, sds_kwargs


def _analyze_program(entry: "_ProgramEntry", sf, args, kwargs) -> None:
    """Capture ``cost_analysis``/``memory_analysis`` for the signature
    just traced. Pays ONE ahead-of-time compile (the jit execution
    cache and the AOT cache are disjoint caches); the guard in
    ``ShardedFunction`` keeps that abstract retrace out of the
    recompile counters."""
    import jax

    try:
        sds_args, sds_kwargs = _abstractify(
            args, kwargs, getattr(sf, "static_argnames", ())
        )
        with sf.uncounted_traces():
            compiled = sf._jitted.lower(
                *sds_args, **sds_kwargs
            ).compile()
    except Exception:
        return
    n = None
    for leaf in jax.tree_util.tree_leaves(args):
        n = _sharding_devices(leaf)
        if n:
            break
    if n:
        entry.n_devices = n
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca:
            entry.flops = float(ca.get("flops", 0.0)) or None
            entry.bytes_accessed = (
                float(ca.get("bytes accessed", 0.0)) or None
            )
        if entry.flops:
            from ray_tpu.telemetry import metrics as tm

            tm.set_program_flops(entry.label, entry.flops)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            entry.memory = {
                "argument_bytes": float(
                    getattr(ma, "argument_size_in_bytes", 0)
                ),
                "output_bytes": float(
                    getattr(ma, "output_size_in_bytes", 0)
                ),
                "temp_bytes": float(
                    getattr(ma, "temp_size_in_bytes", 0)
                ),
                "alias_bytes": float(
                    getattr(ma, "alias_size_in_bytes", 0)
                ),
                "generated_code_bytes": float(
                    getattr(ma, "generated_code_size_in_bytes", 0)
                ),
            }
    except Exception:
        pass


# -- hooks called by sharding/compile.ShardedFunction -------------------


def on_traced(sf, args, kwargs, compile_s: float) -> Optional[str]:
    """One trace (compile) just happened on ``sf``. Records the
    signature, runs the forensics diff against the cached ones, and
    (full mode) captures the program's cost/memory analysis. Returns
    the cause string for retraces beyond the first, else None."""
    if not _enabled:
        return None
    sig = None
    try:
        sig = signature_of(
            args, kwargs, getattr(sf, "static_argnames", ())
        )
    except Exception:
        pass
    with _LOCK:
        entry = _entry_for(sf)
        entry.traces += 1
        entry.compile_time_s += compile_s
        cause = None
        if sig is not None:
            if entry.signatures:
                diff = diff_signatures(entry.signatures[-1], sig)
                cause = cause_string(diff)
                entry.causes.append(
                    {"cause": cause, "diff": diff, "ts": time.time()}
                )
            entry.signatures.append(sig)
            # bound memory on pathological retrace storms
            del entry.signatures[:-16]
            del entry.causes[:-32]
    # one successful analysis per program: the first signature's
    # cost/memory stands for the program (a retrace storm must not pay
    # an extra AOT compile per retrace on top of jit's own)
    if _analyze and entry.flops is None:
        _analyze_program(entry, sf, args, kwargs)
    return cause


def on_aot(sf, compile_s: float, source: str) -> None:
    """``sf`` just installed an AOT executable (sharding/aot.py).
    ``source="aot_cache"`` registers the row with ``compile_s=0`` and
    NO trace — a cache hit is not a compile, and must not feed the
    ``jit:recompile`` forensics. ``source="aot_live"`` is the one
    ahead-of-time compile that seeded the cache: counted exactly like
    a trace so cold-start cost stays visible."""
    if not _enabled:
        return
    with _LOCK:
        entry = _entry_for(sf)
        entry.source = source
        if source == "aot_live":
            entry.traces += 1
            entry.compile_time_s += compile_s


def on_call(sf, t_wall0: float, dt: float, traced: bool = False) -> None:
    """One dispatch of ``sf`` returned after ``dt`` seconds
    (dispatch-side wall; async backends return before the device
    finishes — the next :func:`drain_point` on this thread extends
    the interval to the drain, which is when the work provably
    ended). Calls that traced are compile calls: they don't count as
    executions or busy time, so steady-state MFU stays honest."""
    if not _enabled:
        return
    tid = threading.get_ident()
    now = t_wall0 + dt
    with _LOCK:
        entry = _entry_for(sf)
        stale = _pending.pop(tid, ())
        if not traced:
            entry.executions += 1
            _pending[tid] = [(entry, t_wall0, now)]
    for e, t0, t1 in stale:
        _close(e, t0, t1)
    if not traced:
        _prom_executions(sf.label)


def drain_point() -> None:
    """Close this thread's open program interval at the drain that
    just completed (the RTA005-annotated ONE counted drain): the
    device work is provably finished NOW, so busy time extends from
    dispatch start to here."""
    if not _enabled:
        return
    tid = threading.get_ident()
    with _LOCK:
        open_ = _pending.pop(tid, ())
    now = time.time()
    for e, t0, _t1 in open_:
        _close(e, t0, now)


def _close(entry: "_ProgramEntry", t0: float, t1: float) -> None:
    """Finish one execution interval: accrue busy time, export the
    chrome-trace span on the program's synthetic device lane."""
    t1 = max(t1, t0)
    with _LOCK:
        entry.device_time_s += t1 - t0
    _prom_seconds(entry.label, t1 - t0)
    if tracing.is_enabled():
        tracing.record_spans(
            [
                {
                    "trace_id": "device",
                    "span_id": f"dev-{entry.tid:x}-{next(_span_seq)}",
                    "parent_id": None,
                    "name": f"device:{entry.label}",
                    "start": t0,
                    "end": t1,
                    "attributes": {"program": entry.label},
                    "pid": os.getpid(),
                    "tid": entry.tid,
                    "thread_name": f"device:{entry.label}",
                }
            ]
        )


def _prom_executions(label: str) -> None:
    try:
        from ray_tpu.telemetry import metrics as tm

        tm.inc_program_execution(label)
    except Exception:
        pass


def _prom_seconds(label: str, dt: float) -> None:
    try:
        from ray_tpu.telemetry import metrics as tm

        tm.add_program_device_seconds(label, dt)
    except Exception:
        pass


# -- reads --------------------------------------------------------------


def _flush_all_pending() -> None:
    """Close every thread's open interval at its dispatch-return
    stamp (a snapshot must not leave busy time parked in _pending)."""
    with _LOCK:
        items = list(_pending.items())
        _pending.clear()
    for _tid, open_ in items:
        for e, t0, t1 in open_:
            _close(e, t0, t1)


def recompile_causes() -> Dict[str, List[Dict[str, Any]]]:
    """``{label: [{"cause", "count"}...]}`` rollup of every forensics
    diff recorded so far (``compile_stats()["recompile_causes"]``)."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    with _LOCK:
        entries = list(_entries.values())
    for e in entries:
        if not e.causes:
            continue
        counts: Dict[str, int] = {}
        for c in e.causes:
            counts[c["cause"]] = counts.get(c["cause"], 0) + 1
        out[e.label] = [
            {"cause": k, "count": v} for k, v in counts.items()
        ]
    return out


def snapshot() -> Dict[str, Any]:
    """The ``info/device_ledger`` payload: per-program rows plus the
    aggregate MFU/bytes view. Flushes open execution intervals first."""
    _flush_all_pending()
    kind = device_kind()
    peak = peak_flops_per_device(kind)
    peak_bw = peak_hbm_bytes_per_s(kind)
    with _LOCK:
        entries = list(_entries.values())
    programs = [e.to_dict() for e in entries]
    flops_total = sum(
        (p["flops"] or 0.0) * p["executions"] for p in programs
    )
    bytes_total = sum(
        (p["bytes_accessed"] or 0.0) * p["executions"]
        for p in programs
    )
    busy = sum(
        p["device_time_s"]
        for p in programs
        if p["flops"] is not None and p["executions"]
    )
    n_dev = max((p["n_devices"] for p in programs), default=1)
    totals = {
        "programs": len(programs),
        "executions": sum(p["executions"] for p in programs),
        "device_time_s": round(
            sum(p["device_time_s"] for p in programs), 6
        ),
        "compile_time_s": round(
            sum(p["compile_time_s"] for p in programs), 6
        ),
        "recompiles": sum(p["recompiles"] for p in programs),
        "flops_executed": flops_total,
        "bytes_accessed": bytes_total,
        "mfu": (
            flops_total / (busy * peak * n_dev) if busy > 0 else None
        ),
        "bandwidth_util": (
            bytes_total / (busy * peak_bw * n_dev)
            if busy > 0
            else None
        ),
    }
    return {
        "device_kind": kind,
        "peak_flops_per_device": peak,
        "peak_hbm_bytes_per_s": peak_bw,
        "analyzed": _analyze,
        "programs": programs,
        "totals": totals,
        "recompile_causes": recompile_causes(),
    }


def dump(path: str) -> str:
    """Write the snapshot as JSON (the report CLI's --ledger input)."""
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=1)
    return path
