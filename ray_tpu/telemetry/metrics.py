"""Run-telemetry metric catalog: the aggregate series the training
loop exports (docs/observability.md lists them all).

Counterpart of the reference's component metric defs
(``_private/metrics_agent.py:63`` aggregates per-component OpenCensus
views; ``rllib``'s equivalents live scattered in learner/sampler
stats dicts). Here every series is a process-local
:mod:`ray_tpu.utils.metrics` instrument, scraped through the
``MetricsServer`` the telemetry runtime starts.

All accessors are get-or-create and therefore safe to call from hot
paths without holding module state; instruments live in the global
metric registry (``utils.metrics._REGISTRY``).
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    get_metric,
    timer_histogram,
)

# -- metric names (one place, so docs/tests/dashboards can't drift) ----

ENV_STEPS_PER_S = "ray_tpu_env_steps_per_s"
LEARN_STEPS_PER_S = "ray_tpu_learn_steps_per_s"
ENV_STEPS_TOTAL = "ray_tpu_env_steps_sampled_total"
LEARN_STEPS_TOTAL = "ray_tpu_learn_steps_total"
QUEUE_DEPTH = "ray_tpu_queue_depth"
REQUESTS_IN_FLIGHT = "ray_tpu_requests_in_flight"
DEAD_WORKERS_TOTAL = "ray_tpu_dead_workers_total"
ROLLOUT_WORKERS = "ray_tpu_rollout_workers"
COMPILE_TRACES = "ray_tpu_compile_traces_total"
COMPILE_RECOMPILES = "ray_tpu_compile_recompiles_total"
COMPILE_TIME_S = "ray_tpu_compile_time_seconds_total"
JAX_LIVE_BUFFERS = "ray_tpu_jax_live_buffers"
JAX_DEVICE_MEMORY = "ray_tpu_jax_device_memory_bytes"
OVERLAP_FRACTION = "ray_tpu_iteration_overlap_fraction"
ITERATION_SECONDS = "ray_tpu_iteration_seconds"
# resilience layer (docs/resilience.md)
WORKER_RESTARTS_TOTAL = "ray_tpu_worker_restarts_total"
RECOVERIES_TOTAL = "ray_tpu_recoveries_total"
SKIPPED_BATCHES_TOTAL = "ray_tpu_skipped_batches_total"
# elastic fleets & preemption (docs/resilience.md): rollout-fleet size
# by lifecycle state, preemptions by outcome (drained = graceful exit
# inside the notice window; a lost preemption fell through to the
# ordinary kill path), and the continuous checkpoint stream's
# snapshot count + how many supersteps the written tail lags the run
FLEET_SIZE = "ray_tpu_fleet_size"
PREEMPTIONS_TOTAL = "ray_tpu_preemptions_total"
# learner fleet (docs/fleet.md): hosts in the current mesh epoch and
# the epoch generation itself (a resize shows as the host gauge
# stepping and the generation bumping together), resizes by reason
# (drain vs heartbeat-expired), and AOT pre-seed sweep outcomes by
# aot_warmup status (hit / compiled / disabled)
LEARNER_FLEET_HOSTS = "ray_tpu_learner_fleet_hosts"
MESH_EPOCH = "ray_tpu_mesh_epoch"
MESH_RESIZES_TOTAL = "ray_tpu_mesh_resizes_total"
FLEET_PRESEEDS_TOTAL = "ray_tpu_fleet_aot_preseeds_total"
# fleet-wide observability plane (docs/observability.md "Fleet view",
# telemetry/fleetview.py): per-host barrier wall at each epoch-scoped
# barrier (seconds a host's arrival led the LAST arriver's,
# skew-corrected into the KV clock frame), straggler attribution
# (times a host WAS the last arriver), each exporter's measured clock
# offset against the coordinator's KV clock, how many hosts the
# aggregator currently holds live snapshots for, and the KV
# transport's own round-trip latency measured on the heartbeat path
FLEET_BARRIER_WAIT_SECONDS = "ray_tpu_fleet_barrier_wait_seconds"
FLEET_STRAGGLER_TOTAL = "ray_tpu_fleet_straggler_total"
FLEET_CLOCK_OFFSET_SECONDS = "ray_tpu_fleet_clock_offset_seconds"
FLEET_HOSTS_REPORTING = "ray_tpu_fleet_hosts_reporting"
KV_RTT_SECONDS = "ray_tpu_kv_rtt_seconds"
# fleet control-plane fault tolerance (docs/fleet.md "Failure model &
# leadership"): KV transport retries/reconnects per host, fenced
# (stale-term) coordinator writes the KV store rejected, the leader's
# current lease term, leadership transitions (standby promotions), and
# hosts that self-fenced after losing the KV plane past the liveness
# horizon
KV_RETRIES_TOTAL = "ray_tpu_kv_retries_total"
KV_RECONNECTS_TOTAL = "ray_tpu_kv_reconnects_total"
FLEET_FENCED_WRITES_TOTAL = "ray_tpu_fleet_fenced_writes_total"
FLEET_COORDINATOR_TERM = "ray_tpu_fleet_coordinator_term"
FLEET_FAILOVERS_TOTAL = "ray_tpu_fleet_failovers_total"
FLEET_SELF_FENCES_TOTAL = "ray_tpu_fleet_self_fences_total"
CKPT_STREAM_SNAPSHOTS_TOTAL = (
    "ray_tpu_checkpoint_stream_snapshots_total"
)
CKPT_STREAM_LAG = "ray_tpu_checkpoint_stream_lag_supersteps"
# device-resident data plane (docs/data_plane.md): host→device bytes
# by path — feeder (pipelined transfer), learn (sync learn_on_batch /
# stacked-chain transfer), replay_insert (each transition's ONE
# crossing into a device-resident replay buffer)
H2D_BYTES_TOTAL = "ray_tpu_h2d_bytes_total"
# superstep learner contract (docs/data_plane.md): updates executed
# inside fused K-updates-per-dispatch programs
SUPERSTEP_UPDATES_TOTAL = "ray_tpu_superstep_updates_total"
# prioritized-replay segment-tree operations by op and by which tree
# implementation performed them (docs/data_plane.md "device sum
# tree"): host = the numpy SumSegmentTree walk, device = the
# mesh-resident f64 tree programs. A healthy device-tree run shows
# its sample/update ops under tree="device" and zero under "host".
REPLAY_TREE_OPS_TOTAL = "ray_tpu_replay_tree_ops_total"
# device→host payload bytes by path — the mirror of the H2D counter
# for the readbacks the data plane still performs (today:
# "replay_priorities", the stacked |td| pull that feeds the host
# alpha-power before a device-tree priority refresh)
D2H_BYTES_TOTAL = "ray_tpu_d2h_bytes_total"
# device rollout lane (docs/pipeline.md): env steps taken INSIDE
# mesh-resident rollout programs (JaxVectorEnv lane) — compare against
# ray_tpu_env_steps_sampled_total for the on-device fraction
ENV_STEPS_ON_DEVICE_TOTAL = "ray_tpu_env_steps_on_device_total"
REPLAY_ROWS = "ray_tpu_replay_buffer_rows"
REPLAY_CAPACITY = "ray_tpu_replay_buffer_capacity"
REPLAY_BYTES = "ray_tpu_replay_buffer_bytes"
# param placement (docs/sharding.md "2-D mesh & param partitioning"):
# policy parameter bytes, global vs per-device — at M-way model
# parallelism per_shard sits near global/M; and the count of batch
# leaves whose ragged leading dim forced the replication fallback
# (specs.leaf_sharding) — a nonzero rate means a hot path ships
# full-copy columns it meant to row-shard
PARAMS_BYTES = "ray_tpu_params_bytes"
SHARDING_FALLBACK_TOTAL = (
    "ray_tpu_sharding_fallback_replicated_total"
)
# inference plane (docs/serving.md): the continuous-batching policy
# server's queue depth, coalesced forward batch sizes, request count,
# end-to-end request latency (p50/p99 read off the histogram or the
# server's exact stats()), and the params version the replica serves
# (bumps on checkpoint hot-reload)
SERVE_QUEUE_DEPTH = "ray_tpu_serve_queue_depth"
SERVE_BATCH_SIZE = "ray_tpu_serve_batch_size"
SERVE_REQUESTS_TOTAL = "ray_tpu_serve_requests_total"
SERVE_LATENCY_SECONDS = "ray_tpu_serve_latency_seconds"
SERVE_PARAMS_VERSION = "ray_tpu_serve_params_version"
# serve-plane batch observability (docs/serving.md): occupancy of the
# executed bucket (1.0 = every padded row was real work) and how long
# a request waited in the queue before its batch launched
SERVE_BATCH_FILL_FRACTION = "ray_tpu_serve_batch_fill_fraction"
SERVE_QUEUE_WAIT_SECONDS = "ray_tpu_serve_queue_wait_seconds"
# ingress front door (docs/serving.md "the front door",
# ray_tpu/ingress/): per-route request counts by HTTP status, admitted
# requests currently in flight, sheds by reason (inflight budget /
# queue-wait / expired deadline), and end-to-end ingress latency
INGRESS_REQUESTS_TOTAL = "ray_tpu_ingress_requests_total"
INGRESS_INFLIGHT = "ray_tpu_ingress_inflight"
INGRESS_SHED_TOTAL = "ray_tpu_ingress_shed_total"
INGRESS_LATENCY_SECONDS = "ray_tpu_ingress_latency_seconds"
# multi-process front door (ingress/supervisor.py): live worker
# processes in the bank, workers respawned after a crash, and admitted
# in-flight per policy (the per-tenant quota's observable)
INGRESS_WORKERS = "ray_tpu_ingress_workers"
INGRESS_WORKER_RESPAWNS_TOTAL = (
    "ray_tpu_ingress_worker_respawns_total"
)
INGRESS_POLICY_INFLIGHT = "ray_tpu_ingress_policy_inflight"
# open-loop flood harness (bench.py --flood): offered vs achieved
# rate of the CURRENT sweep step, and responses by contract outcome
# (ok / shed_429 / shed_503 / expired_504)
FLOOD_OFFERED_RPS = "ray_tpu_flood_offered_rps"
FLOOD_GOODPUT_RPS = "ray_tpu_flood_goodput_rps"
FLOOD_RESPONSES_TOTAL = "ray_tpu_flood_responses_total"
# cross-replica coalescing router (ingress/router.py): dispatched
# buckets, rows merged into them, requests dropped at their deadline
# BEFORE dispatch, and batches re-routed off a dead replica
ROUTER_BATCHES_TOTAL = "ray_tpu_router_batches_total"
ROUTER_MERGED_ROWS_TOTAL = "ray_tpu_router_merged_rows_total"
ROUTER_EXPIRED_TOTAL = "ray_tpu_router_expired_total"
ROUTER_REROUTED_TOTAL = "ray_tpu_router_rerouted_total"
# AOT compiled-program cache (sharding/aot.py): hit/miss/save plus
# the failure lanes (load_error/save_error → misses; fallback = an
# installed executable rejected at dispatch, reverted to live jit)
AOT_CACHE_EVENTS_TOTAL = "ray_tpu_aot_cache_events_total"
# device-plane program ledger (docs/observability.md "device ledger",
# telemetry/device.py): per compiled program — steady-state execution
# count, cumulative device-busy seconds closed at the drain points,
# and the program's per-execution FLOPs from cost_analysis()
PROGRAM_EXECUTIONS_TOTAL = "ray_tpu_program_executions_total"
PROGRAM_DEVICE_SECONDS_TOTAL = "ray_tpu_program_device_seconds_total"
PROGRAM_FLOPS = "ray_tpu_program_flops"


def gauge(
    name: str, description: str = "", tag_keys=()
) -> Gauge:
    """Get-or-create a Gauge (idempotent, like timer_histogram)."""
    m = get_metric(name)
    if isinstance(m, Gauge):
        return m
    return Gauge(name, description, tag_keys=tag_keys)


def counter(
    name: str, description: str = "", tag_keys=()
) -> Counter:
    m = get_metric(name)
    if isinstance(m, Counter):
        return m
    return Counter(name, description, tag_keys=tag_keys)


def histogram(name: str, description: str = "") -> Histogram:
    return timer_histogram(name, description)


# -- pipeline gauges (called from the execution layer) -----------------


def set_queue_depth(queue_name: str, depth: int) -> None:
    """Depth of one bounded pipeline queue (feeder in/out, learner
    in/out, prefetch) — the saturation signal of docs/pipeline.md."""
    gauge(
        QUEUE_DEPTH,
        "bounded pipeline queue depth",
        ("queue",),
    ).set(float(depth), {"queue": queue_name})


def set_requests_in_flight(manager: str, n: int) -> None:
    gauge(
        REQUESTS_IN_FLIGHT,
        "outstanding sample requests per AsyncRequestsManager",
        ("manager",),
    ).set(float(n), {"manager": manager})


def inc_dead_workers(manager: str, n: int = 1) -> None:
    counter(
        DEAD_WORKERS_TOTAL,
        "rollout workers observed dead",
        ("manager",),
    ).inc(float(n), {"manager": manager})


def inc_worker_restarts(n: int = 1) -> None:
    """Rollout workers recreated after observed death (fed by
    WorkerSet.replace_failed_workers / recreate_failed_workers)."""
    counter(
        WORKER_RESTARTS_TOTAL,
        "rollout workers recreated after failure",
    ).inc(float(n))


def inc_recoveries(kind: str, n: int = 1) -> None:
    """Recovery actions taken by the RecoveryManager, by kind
    (``workers`` = fleet probe+recreate, ``restore`` =
    checkpoint auto-restore)."""
    counter(
        RECOVERIES_TOTAL,
        "training-loop recovery actions",
        ("kind",),
    ).inc(float(n), {"kind": kind})


def inc_skipped_batches(n: int = 1) -> None:
    """Learn batches skipped by the non-finite guard (nan_guard)."""
    counter(
        SKIPPED_BATCHES_TOTAL,
        "learn batches skipped by the non-finite guard",
    ).inc(float(n))


def set_fleet_size(
    active: int, draining: int = 0, joining: int = 0
) -> None:
    """Rollout-fleet size by lifecycle state (set by the
    FleetController on every transition; docs/resilience.md fleet
    state machine)."""
    g = gauge(
        FLEET_SIZE,
        "rollout workers by fleet lifecycle state",
        ("state",),
    )
    g.set(float(active), {"state": "active"})
    g.set(float(draining), {"state": "draining"})
    g.set(float(joining), {"state": "joining"})


def inc_preemptions(drained: bool, n: int = 1) -> None:
    """Worker preemptions observed, split by outcome: ``drained`` =
    the eviction notice was honored (graceful exit, zero recovery
    budget); otherwise the preemption fell through to the ordinary
    kill/recovery path."""
    counter(
        PREEMPTIONS_TOTAL,
        "worker preemptions by drain outcome",
        ("drained",),
    ).inc(float(n), {"drained": "true" if drained else "false"})


def set_learner_fleet(hosts: int, gen: int) -> None:
    """Learner-fleet geometry under the current mesh epoch (set by
    the FleetCoordinator on every epoch cut; docs/fleet.md)."""
    gauge(
        LEARNER_FLEET_HOSTS,
        "learner hosts in the current mesh epoch",
    ).set(float(hosts))
    gauge(
        MESH_EPOCH,
        "current learner mesh epoch generation",
    ).set(float(gen))


def inc_mesh_resizes(reason: str, n: int = 1) -> None:
    """Learner-mesh resizes by reason (``preempted`` = notice-driven
    drain, ``heartbeat-expired`` = crashed host swept by liveness)."""
    counter(
        MESH_RESIZES_TOTAL,
        "learner mesh resizes",
        ("reason",),
    ).inc(float(n), {"reason": reason})


def set_barrier_wait(host: str, epoch: int, seconds: float) -> None:
    """How long ``host``'s arrival at the latest epoch-scoped barrier
    led the LAST arriver's (0 for the straggler itself) — the per-host
    DCN stall attribution the fleet aggregator computes from KV
    arrival records, skew-corrected into the coordinator's KV clock
    frame (docs/observability.md "Fleet view")."""
    gauge(
        FLEET_BARRIER_WAIT_SECONDS,
        "seconds a host waited on the barrier's last arriver",
        ("host", "epoch"),
    ).set(float(seconds), {"host": host, "epoch": str(epoch)})


def inc_straggler(host: str, n: int = 1) -> None:
    """One barrier where ``host`` was the LAST arriver (the fleet's
    measured straggler)."""
    counter(
        FLEET_STRAGGLER_TOTAL,
        "barriers where this host arrived last",
        ("host",),
    ).inc(float(n), {"host": host})


def set_clock_offset(host: str, seconds: float) -> None:
    """``host``'s wall clock minus the coordinator's KV clock, as
    measured by the exporter's NTP-style handshake (positive = the
    host's clock runs ahead)."""
    gauge(
        FLEET_CLOCK_OFFSET_SECONDS,
        "host wall clock minus the coordinator KV clock",
        ("host",),
    ).set(float(seconds), {"host": host})


def set_hosts_reporting(n: int) -> None:
    """Hosts the fleet aggregator currently holds a live (non-aged)
    snapshot for."""
    gauge(
        FLEET_HOSTS_REPORTING,
        "hosts with a live snapshot at the fleet aggregator",
    ).set(float(n))


def set_kv_rtt(host: str, seconds: float) -> None:
    """Round-trip latency of one KV heartbeat as measured by this
    host's HeartbeatReporter — the fleet plane's own transport
    health."""
    gauge(
        KV_RTT_SECONDS,
        "KV heartbeat round-trip seconds measured per host",
        ("host",),
    ).set(float(seconds), {"host": host})


def inc_fleet_preseed(status: str, n: int = 1) -> None:
    """Resize-geometry AOT pre-seed attempts by aot_warmup outcome."""
    counter(
        FLEET_PRESEEDS_TOTAL,
        "resize-geometry AOT pre-seed attempts",
        ("status",),
    ).inc(float(n), {"status": status})


def inc_kv_retries(host: str, op: str, n: int = 1) -> None:
    """KV ops this host re-attempted after a transient transport
    failure (the retried KV transport's backoff schedule fired)."""
    counter(
        KV_RETRIES_TOTAL,
        "KV ops retried after a transient transport failure",
        ("host", "op"),
    ).inc(float(n), {"host": host, "op": op})


def inc_kv_reconnects(host: str, n: int = 1) -> None:
    """KV control-plane threads (subscriber / heartbeat / exporter) on
    this host that re-established service after an outage window."""
    counter(
        KV_RECONNECTS_TOTAL,
        "control-plane threads that reconnected after a KV outage",
        ("host",),
    ).inc(float(n), {"host": host})


def inc_fleet_fenced_write(host: str, n: int = 1) -> None:
    """Coordinator writes rejected by the KV store for carrying a
    stale lease term — each one is a split-brain write that did NOT
    happen (``host`` is the zombie writer's lease holder identity)."""
    counter(
        FLEET_FENCED_WRITES_TOTAL,
        "stale-term coordinator writes rejected by the KV store",
        ("host",),
    ).inc(float(n), {"host": host})


def set_coordinator_term(host: str, term: int) -> None:
    """The lease term under which ``host``'s coordinator currently
    holds fleet leadership (bumps on every failover)."""
    gauge(
        FLEET_COORDINATOR_TERM,
        "lease term of this host's fleet coordinator",
        ("host",),
    ).set(float(term), {"host": host})


def inc_fleet_failover(host: str, n: int = 1) -> None:
    """Leadership transitions: a standby coordinator on ``host``
    acquired the fleet lease after the previous leader let it lapse."""
    counter(
        FLEET_FAILOVERS_TOTAL,
        "standby coordinators promoted to fleet leadership",
        ("host",),
    ).inc(float(n), {"host": host})


def inc_self_fence(host: str, n: int = 1) -> None:
    """Times this host parked at its epoch boundary because it could
    not reach KV past the liveness horizon (partition self-fencing:
    the mesh may have re-formed without it)."""
    counter(
        FLEET_SELF_FENCES_TOTAL,
        "hosts parked at an epoch boundary on a KV partition",
        ("host",),
    ).inc(float(n), {"host": host})


def inc_stream_snapshots(n: int = 1) -> None:
    """Snapshots written by the continuous CheckpointStreamer."""
    counter(
        CKPT_STREAM_SNAPSHOTS_TOTAL,
        "continuous checkpoint stream snapshots written",
    ).inc(float(n))


def set_stream_lag(supersteps: int) -> None:
    """How many supersteps the written stream tail lags the live run
    (the work-lost bound on a driver crash)."""
    gauge(
        CKPT_STREAM_LAG,
        "supersteps between the run head and the written stream tail",
    ).set(float(supersteps))


def inc_superstep_updates(n: int = 1) -> None:
    """Learner updates executed inside fused superstep programs (K
    updates per dispatch — docs/data_plane.md). Compare against
    ``ray_tpu_learn_steps_total`` for the fused fraction."""
    counter(
        SUPERSTEP_UPDATES_TOTAL,
        "learner updates run inside fused superstep dispatches",
    ).inc(float(n))


def inc_env_steps_on_device(n: int) -> None:
    """Env steps executed inside a device rollout program (the
    JaxVectorEnv lane — zero rollout bytes over H2D)."""
    counter(
        ENV_STEPS_ON_DEVICE_TOTAL,
        "env steps taken inside mesh-resident rollout programs",
    ).inc(float(n))


def add_h2d_bytes(path: str, n: int) -> None:
    """Host→device payload bytes about to cross the wire on ``path``
    (``feeder`` | ``learn`` | ``replay_insert`` | ``rollout`` — the
    device rollout lane's key stacks, its entire payload). The byte
    diet of
    docs/data_plane.md is read off this counter: a device-resident
    replay run moves each transition once (``replay_insert``) instead
    of once per learn step (``learn``)."""
    if n <= 0:
        return
    counter(
        H2D_BYTES_TOTAL,
        "host to device payload bytes by transfer path",
        ("path",),
    ).inc(float(n), {"path": path})


def inc_tree_op(op: str, tree: str, n: int = 1) -> None:
    """One segment-tree operation on the prioritized-replay path:
    ``op`` ∈ insert | update | sample, ``tree`` ∈ host | device
    (which implementation walked the tree)."""
    counter(
        REPLAY_TREE_OPS_TOTAL,
        "prioritized-replay segment-tree ops by op and tree plane",
        ("op", "tree"),
    ).inc(float(n), {"op": op, "tree": tree})


def add_d2h_bytes(path: str, n: int) -> None:
    """Device→host payload bytes about to cross on ``path``
    (``replay_priorities``: the stacked |td| pull for the host
    alpha-power — docs/data_plane.md documents why that transform
    stays host-side)."""
    if n <= 0:
        return
    counter(
        D2H_BYTES_TOTAL,
        "device to host payload bytes by transfer path",
        ("path",),
    ).inc(float(n), {"path": path})


def d2h_bytes_by_path() -> Dict[str, float]:
    """Per-path totals of the D2H byte counter ({} before any
    readback) — same shape as :func:`h2d_bytes_by_path`."""
    m = get_metric(D2H_BYTES_TOTAL)
    if m is None:
        return {}
    out: Dict[str, float] = {}
    for tags, v in m.series():
        path = dict(tags).get("path", "")
        out[path] = out.get(path, 0.0) + v
    return out


def set_replay_occupancy(
    policy_id: str, rows: int, capacity: int, nbytes: int,
    device: bool,
) -> None:
    """Occupancy of one replay buffer (device-resident or the host
    spill fallback): stored rows, row capacity, and resident storage
    bytes (for device buffers this is HBM/accelerator memory)."""
    tags = {
        "policy": policy_id,
        "storage": "device" if device else "host",
    }
    gauge(
        REPLAY_ROWS, "replay buffer stored rows", ("policy", "storage")
    ).set(float(rows), tags)
    gauge(
        REPLAY_CAPACITY,
        "replay buffer row capacity",
        ("policy", "storage"),
    ).set(float(capacity), tags)
    gauge(
        REPLAY_BYTES,
        "replay buffer resident storage bytes",
        ("policy", "storage"),
    ).set(float(nbytes), tags)


def set_params_bytes(
    policy: str, global_bytes: int, per_shard_bytes: int
) -> None:
    """Parameter memory of one policy, next to the replay/live-buffer
    gauges: ``global`` = the full tree, ``per_shard`` = what one
    device actually holds under the active placement (equal when
    replicated; ~global/M at M-way model parallelism)."""
    g = gauge(
        PARAMS_BYTES,
        "policy parameter bytes by placement",
        ("policy", "placement"),
    )
    g.set(float(global_bytes), {"policy": policy, "placement": "global"})
    g.set(
        float(per_shard_bytes),
        {"policy": policy, "placement": "per_shard"},
    )


def inc_sharding_fallback(n: int = 1) -> None:
    """Batch leaves replicated by the ragged-leading-dim fallback in
    ``sharding.specs.leaf_sharding`` (should be 0 on a healthy hot
    path)."""
    counter(
        SHARDING_FALLBACK_TOTAL,
        "batch leaves replicated by the ragged-leading-dim fallback",
    ).inc(float(n))


def set_serve_queue_depth(deployment: str, depth: int) -> None:
    """Requests waiting in one policy server's batch queue — the
    serve-plane saturation signal the queue-wait autoscaler keys off
    (docs/serving.md)."""
    gauge(
        SERVE_QUEUE_DEPTH,
        "policy-server requests waiting to be batched",
        ("deployment",),
    ).set(float(depth), {"deployment": deployment})


def observe_serve_batch(deployment: str, rows: int) -> None:
    """Size of one coalesced forward batch (pre-padding): the
    continuous-batching efficiency signal — a p50 near 1 under load
    means the batcher is flushing too eagerly."""
    m = get_metric(SERVE_BATCH_SIZE)
    if not isinstance(m, Histogram):
        m = Histogram(
            SERVE_BATCH_SIZE,
            "coalesced policy-server forward batch rows",
            boundaries=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
            tag_keys=("deployment",),
        )
    m.observe(float(rows), {"deployment": deployment})


def inc_serve_requests(deployment: str, n: int = 1) -> None:
    counter(
        SERVE_REQUESTS_TOTAL,
        "policy-server requests accepted",
        ("deployment",),
    ).inc(float(n), {"deployment": deployment})


def observe_serve_latency(deployment: str, seconds: float) -> None:
    """End-to-end request latency (submit → result ready): queue wait
    + batch assembly + the sharded forward + scatter."""
    m = get_metric(SERVE_LATENCY_SECONDS)
    if not isinstance(m, Histogram):
        m = Histogram(
            SERVE_LATENCY_SECONDS,
            "policy-server request latency seconds",
            tag_keys=("deployment",),
        )
    m.observe(float(seconds), {"deployment": deployment})


def set_serve_batch_fill(deployment: str, fill: float) -> None:
    """Occupancy of the bucket the last forward executed: real rows /
    bucket rows (post-padding). A sustained low fill means the batcher
    flushes under-full buckets — wasted device work per request."""
    gauge(
        SERVE_BATCH_FILL_FRACTION,
        "real rows / executed bucket rows of the last serve batch",
        ("deployment",),
    ).set(float(fill), {"deployment": deployment})


def observe_serve_queue_wait(deployment: str, seconds: float) -> None:
    """Time one request sat in the batch queue before its forward
    launched — the queue-wait component of the end-to-end latency
    histogram (and the autoscaler's saturation signal, exact
    percentiles in the server's stats())."""
    m = get_metric(SERVE_QUEUE_WAIT_SECONDS)
    if not isinstance(m, Histogram):
        m = Histogram(
            SERVE_QUEUE_WAIT_SECONDS,
            "policy-server request queue-wait seconds",
            tag_keys=("deployment",),
        )
    m.observe(float(seconds), {"deployment": deployment})


def inc_ingress_request(route: str, status: int) -> None:
    """One HTTP request answered by the ingress front door, by route
    and final status code (2xx served, 429/503 shed, 504 expired)."""
    counter(
        INGRESS_REQUESTS_TOTAL,
        "ingress HTTP requests by route and status",
        ("route", "status"),
    ).inc(1.0, {"route": route, "status": str(status)})


def set_ingress_inflight(n: int) -> None:
    """Requests admitted past the front door and not yet answered —
    the admission controller's bounded budget."""
    gauge(
        INGRESS_INFLIGHT,
        "admitted ingress requests currently in flight",
    ).set(float(n))


def inc_ingress_shed(reason: str, n: int = 1) -> None:
    """One request shed at the ingress: ``inflight`` (budget
    exhausted → 429), ``quota`` (the POLICY's in-flight share
    exhausted → 429), ``queue_wait`` (replica waits over target →
    503), or ``deadline`` (already expired on arrival → 504)."""
    counter(
        INGRESS_SHED_TOTAL,
        "requests shed by the admission controller, by reason",
        ("reason",),
    ).inc(float(n), {"reason": reason})


def observe_ingress_latency(route: str, seconds: float) -> None:
    """End-to-end ingress latency: socket accept to response write —
    the number a client actually experiences (queue wait + coalesce +
    forward + serialization)."""
    m = get_metric(INGRESS_LATENCY_SECONDS)
    if not isinstance(m, Histogram):
        m = Histogram(
            INGRESS_LATENCY_SECONDS,
            "end-to-end ingress request latency seconds",
            tag_keys=("route",),
        )
    m.observe(float(seconds), {"route": route})


def set_ingress_workers(state: str, n: int) -> None:
    """Worker-process census of the multi-process front door bank
    (ingress/supervisor.py): ``state="live"`` is the processes
    currently accepting on the shared port; ``state="target"`` the
    configured bank size."""
    gauge(
        INGRESS_WORKERS,
        "ingress worker processes by state",
        ("state",),
    ).set(float(n), {"state": state})


def inc_ingress_worker_respawns(n: int = 1) -> None:
    """One crashed ingress worker the supervisor replaced (the bank
    keeps accepting on the shared port throughout)."""
    counter(
        INGRESS_WORKER_RESPAWNS_TOTAL,
        "ingress worker processes respawned after a crash",
    ).inc(float(n))


def set_ingress_policy_inflight(policy: str, n: int) -> None:
    """Admitted in-flight requests of ONE policy — the observable the
    per-tenant quota bounds (shed reason ``quota`` fires when a
    policy's next request would exceed its share)."""
    gauge(
        INGRESS_POLICY_INFLIGHT,
        "admitted in-flight ingress requests per policy",
        ("policy",),
    ).set(float(n), {"policy": policy})


def set_flood_offered_rps(rps: float) -> None:
    """Open-loop offered arrival rate of the flood harness's current
    sweep step (arrivals are scheduled, never gated on responses)."""
    gauge(
        FLOOD_OFFERED_RPS,
        "flood harness offered arrival rate (open loop)",
    ).set(float(rps))


def set_flood_goodput_rps(rps: float) -> None:
    """In-deadline 200 responses per second the mesh actually
    sustained at the current offered rate — goodput, not throughput."""
    gauge(
        FLOOD_GOODPUT_RPS,
        "flood harness in-deadline 200 responses per second",
    ).set(float(rps))


def inc_flood_response(kind: str, n: int = 1) -> None:
    """One flood response by contract outcome: ``ok`` (200 within
    deadline), ``shed_429`` / ``shed_503`` / ``expired_504`` (the
    overload contract), ``late_200`` (a 200 past its deadline — a
    contract VIOLATION the harness asserts never happens), or
    ``error``."""
    counter(
        FLOOD_RESPONSES_TOTAL,
        "flood harness responses by contract outcome",
        ("kind",),
    ).inc(float(n), {"kind": kind})


def observe_router_batch(deployment: str, rows: int) -> None:
    """One coalesced bucket the router dispatched to a replica, with
    the rows merged into it (cross-request, cross-connection)."""
    counter(
        ROUTER_BATCHES_TOTAL,
        "coalesced buckets dispatched by the router",
        ("deployment",),
    ).inc(1.0, {"deployment": deployment})
    counter(
        ROUTER_MERGED_ROWS_TOTAL,
        "rows merged into dispatched router buckets",
        ("deployment",),
    ).inc(float(rows), {"deployment": deployment})


def inc_router_expired(deployment: str, n: int = 1) -> None:
    """Requests the router dropped at their deadline BEFORE dispatch
    (no dead device work was computed for them)."""
    counter(
        ROUTER_EXPIRED_TOTAL,
        "requests dropped at their deadline before dispatch",
        ("deployment",),
    ).inc(float(n), {"deployment": deployment})


def inc_router_rerouted(deployment: str, n: int = 1) -> None:
    """Requests re-queued off a replica that died mid-dispatch and
    routed to a surviving one."""
    counter(
        ROUTER_REROUTED_TOTAL,
        "requests rerouted off dead replicas",
        ("deployment",),
    ).inc(float(n), {"deployment": deployment})


def inc_aot_cache_event(event: str, n: int = 1) -> None:
    """AOT compile-cache traffic (sharding/aot.py): hit / miss / save
    / load_error / save_error / fallback."""
    counter(
        AOT_CACHE_EVENTS_TOTAL,
        "AOT compiled-program cache events",
        ("event",),
    ).inc(float(n), {"event": event})


def inc_program_execution(program: str, n: int = 1) -> None:
    """One steady-state execution of a compiled device program
    (traced/compile calls excluded — telemetry/device.py)."""
    counter(
        PROGRAM_EXECUTIONS_TOTAL,
        "compiled-program executions by program label",
        ("program",),
    ).inc(float(n), {"program": program})


def add_program_device_seconds(program: str, seconds: float) -> None:
    """Device-busy wall seconds accrued by one program's execution
    interval (dispatch start → drain point)."""
    if seconds <= 0:
        return
    counter(
        PROGRAM_DEVICE_SECONDS_TOTAL,
        "cumulative device-busy seconds by program label",
        ("program",),
    ).inc(float(seconds), {"program": program})


def set_program_flops(program: str, flops: float) -> None:
    """Per-execution FLOPs of a compiled program (XLA
    ``cost_analysis()``, captured once per traced signature)."""
    gauge(
        PROGRAM_FLOPS,
        "per-execution FLOPs of a compiled program (cost_analysis)",
        ("program",),
    ).set(float(flops), {"program": program})


def set_serve_params_version(deployment: str, version: int) -> None:
    """Monotonic params version a policy server is serving; bumps
    exactly once per applied checkpoint hot-reload."""
    gauge(
        SERVE_PARAMS_VERSION,
        "params version served (bumps on checkpoint hot-reload)",
        ("deployment",),
    ).set(float(version), {"deployment": deployment})


def h2d_bytes_by_path() -> Dict[str, float]:
    """Current per-path totals of the H2D byte counter ({} before any
    transfer). Algorithm.step diffs this across an iteration for the
    ``info/telemetry`` byte roll-up."""
    m = get_metric(H2D_BYTES_TOTAL)
    if m is None:
        return {}
    out: Dict[str, float] = {}
    for tags, v in m.series():
        path = dict(tags).get("path", "")
        out[path] = out.get(path, 0.0) + v
    return out


def counter_total(name: str) -> float:
    """Sum of a counter's series across all tag values (0.0 when the
    counter was never touched)."""
    m = get_metric(name)
    if m is None:
        return 0.0
    return sum(v for _, v in m.series())


def learn_steps_total() -> float:
    """Cumulative SGD programs dispatched in this process (fed by
    JaxPolicy.learn_on_device_batch); Algorithm.step diffs it across
    an iteration for the learn-steps/s gauge."""
    m = get_metric(LEARN_STEPS_TOTAL)
    if m is None:
        return 0.0
    return sum(v for _, v in m.series())


# -- per-iteration runtime sampling (called by Algorithm.step) ---------


def sample_runtime_gauges() -> Dict[str, float]:
    """Refresh the process-level gauges that must be polled: the
    sharded_jit compile cache and jax's live-buffer/device-memory
    state. Returns the sampled values (reported under
    ``info/telemetry`` too). Cheap enough for once-per-iteration."""
    out: Dict[str, float] = {}
    try:
        from ray_tpu.sharding.compile import compile_stats

        cs = compile_stats()
        gauge(
            COMPILE_TRACES, "sharded_jit traces (process-wide)"
        ).set(float(cs["traces"]))
        gauge(
            COMPILE_RECOMPILES,
            "sharded_jit recompiles beyond first trace",
        ).set(float(cs["recompiles"]))
        gauge(
            COMPILE_TIME_S, "cumulative sharded_jit compile seconds"
        ).set(float(cs["compile_time_s"]))
        out["compile_traces"] = float(cs["traces"])
        out["compile_recompiles"] = float(cs["recompiles"])
        out["compile_time_s"] = float(cs["compile_time_s"])
    except Exception:
        pass
    try:
        import jax

        n_live = len(jax.live_arrays())
        gauge(
            JAX_LIVE_BUFFERS, "live jax arrays in this process"
        ).set(float(n_live))
        out["jax_live_buffers"] = float(n_live)
        mem: Optional[dict] = None
        try:
            mem = jax.local_devices()[0].memory_stats()
        except Exception:
            mem = None
        if mem and "bytes_in_use" in mem:
            # per-device resident bytes (TPU/GPU backends; the CPU
            # client reports no memory_stats — gauge simply absent)
            g = gauge(
                JAX_DEVICE_MEMORY,
                "bytes in use on the learner devices",
                ("device",),
            )
            total = 0.0
            for i, d in enumerate(jax.local_devices()):
                stats = d.memory_stats() or {}
                b = float(stats.get("bytes_in_use", 0.0))
                g.set(b, {"device": str(i)})
                total += b
            out["device_memory_bytes"] = total
    except Exception:
        pass
    return out


def record_iteration_throughput(
    env_steps: float, learn_steps: float, wall_s: float
) -> Dict[str, float]:
    """Set the per-iteration throughput gauges; returns the values for
    the ``info/telemetry`` roll-up."""
    wall_s = max(wall_s, 1e-9)
    env_rate = env_steps / wall_s
    learn_rate = learn_steps / wall_s
    gauge(
        ENV_STEPS_PER_S, "env steps sampled per second (last iter)"
    ).set(env_rate)
    gauge(
        LEARN_STEPS_PER_S, "learner SGD programs per second (last iter)"
    ).set(learn_rate)
    counter(ENV_STEPS_TOTAL, "env steps sampled").inc(
        max(0.0, float(env_steps))
    )
    histogram(
        ITERATION_SECONDS, "train-iteration wall seconds"
    ).observe(wall_s)
    return {
        "env_steps_per_s": env_rate,
        "learn_steps_per_s": learn_rate,
    }
