"""Device mesh helpers for the learner plane.

This is where the reference's multi-GPU tower machinery
(``rllib/policy/torch_policy.py:498-624``: per-device replicas, loader
threads, CPU grad averaging) collapses into JAX sharding: one mesh, one
jitted update, XLA collectives over ICI.

Axis conventions used across ray_tpu:
  - "data": batch data parallelism (the parity axis with the reference)
  - "model": tensor parallelism for large learner models (TPU extension)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def get_devices(platform: Optional[str] = None):
    devs = jax.devices()
    if platform:
        devs = [d for d in devs if d.platform == platform]
    return devs


def make_mesh(
    axis_shapes: Optional[Sequence[Tuple[str, int]]] = None,
    devices=None,
) -> Mesh:
    """Build a mesh; default is a 1-D data mesh over all devices."""
    devices = devices if devices is not None else jax.devices()
    if axis_shapes is None:
        axis_shapes = [(DATA_AXIS, len(devices))]
    names = tuple(n for n, _ in axis_shapes)
    shape = tuple(s for _, s in axis_shapes)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, names)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-dim batch sharding."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def num_data_shards(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]
