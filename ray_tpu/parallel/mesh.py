"""Legacy device-mesh helpers — now an adapter over ``ray_tpu.sharding``.

This module predates the sharding runtime (``ray_tpu/sharding/``): its
``("data",)`` axis naming is kept for the pmap-backend learn programs
and the multi-host worker scripts that still build meshes here. New
code should use ``ray_tpu.sharding`` directly (axis ``"batch"``); the
helpers below all derive the axis from the mesh object, so they accept
meshes from either namespace.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.sharding import mesh as _mesh_rt

DATA_AXIS = "data"
MODEL_AXIS = "model"


def get_devices(platform: Optional[str] = None):
    return _mesh_rt.available_devices(platform)


def make_mesh(
    axis_shapes: Optional[Sequence[Tuple[str, int]]] = None,
    devices=None,
) -> Mesh:
    """Build a mesh; default is a 1-D ("data",) mesh over all devices
    (the legacy axis name — the sharding runtime's default is
    ("batch",))."""
    if devices is None:
        devices = jax.devices()
    if axis_shapes is None:
        axis_shapes = [(DATA_AXIS, len(devices))]
    return _mesh_rt.get_mesh(devices=devices, axis_shapes=axis_shapes)


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-dim batch sharding (axis name taken from the mesh)."""
    return NamedSharding(mesh, P(_mesh_rt.data_axis(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def num_data_shards(mesh: Mesh) -> int:
    return _mesh_rt.num_shards(mesh)
