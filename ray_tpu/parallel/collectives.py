"""Collective communication API.

Counterpart of the reference's ``ray.util.collective``
(``util/collective/collective.py:120-615``: init_collective_group,
allreduce :258, broadcast :373, allgather :423, reducescatter :472,
send/recv :531,594 over NCCL/Gloo groups).

TPU-first disposition (SURVEY §5.8): on-device collectives are XLA
primitives over mesh axes — there is no group bootstrap, no NCCL
communicator, no rendezvous KV; a Mesh IS the group. This module provides:

  1. The device-plane API: named wrappers usable inside ``shard_map``
     bodies, one per reference verb (allreduce→psum, allgather,
     reducescatter→psum_scatter, broadcast, send/recv→ppermute shift).
  2. A host-plane ``Group`` for CPU actor fleets (the Gloo role):
     driver-mediated reduction across actor handles, used by
     DDPPO-style decentralized training.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Device plane (inside shard_map over a mesh axis)
# ---------------------------------------------------------------------------


def allreduce(x, axis_name: str, op: str = "sum"):
    """reference collective.py:258 (NCCL allreduce) → XLA psum/pmax/..."""
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op == "mean":
        return jax.lax.pmean(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    raise ValueError(f"unknown op {op}")


def allgather(x, axis_name: str, axis: int = 0):
    """reference collective.py:423 → lax.all_gather (concatenated)."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def reducescatter(x, axis_name: str, scatter_axis: int = 0):
    """reference collective.py:472 → lax.psum_scatter."""
    return jax.lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_axis, tiled=True
    )


def broadcast(x, axis_name: str, src: int = 0):
    """reference collective.py:373: every shard gets shard ``src``'s
    value. Implemented as a masked psum (zero elsewhere)."""
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def send_recv_shift(x, axis_name: str, shift: int = 1):
    """reference send/recv :531,594 — on an ICI ring the idiom is a
    permute shift: every shard sends to (rank+shift) and receives from
    (rank-shift)."""
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def barrier(axis_name: str):
    """reference collective.py barrier — a psum of a scalar."""
    return jax.lax.psum(jnp.ones(()), axis_name)


# ---------------------------------------------------------------------------
# Host plane (CPU actor fleets; the Gloo-group role)
# ---------------------------------------------------------------------------

_OPS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
}


class HostGroup:
    """Driver-mediated collective over actor handles (reference
    GLOOGroup ``gloo_collective_group.py:184``, scoped to the
    driver-as-root topology). Each verb fans out actor calls, reduces on
    the driver, and fans the result back — one shm broadcast each way."""

    def __init__(self, actors: Sequence):
        self.actors = list(actors)

    @property
    def world_size(self) -> int:
        return len(self.actors)

    def allreduce(
        self, get_method: str, set_method: str, op: str = "mean"
    ) -> np.ndarray:
        """Gather `a.<get_method>()` from every actor, reduce, push the
        result back via `a.<set_method>(reduced)`."""
        import ray_tpu as ray

        vals = ray.get(
            [getattr(a, get_method).remote() for a in self.actors]
        )
        leaves_list = [jax.tree_util.tree_leaves(v) for v in vals]
        treedef = jax.tree_util.tree_structure(vals[0])
        reduced_leaves = [
            _OPS[op]([np.asarray(l[i]) for l in leaves_list])
            for i in range(len(leaves_list[0]))
        ]
        reduced = jax.tree_util.tree_unflatten(
            treedef, reduced_leaves
        )
        ref = ray.put(reduced)
        ray.get(
            [getattr(a, set_method).remote(ref) for a in self.actors]
        )
        return reduced

    def gather(self, get_method: str) -> List:
        import ray_tpu as ray

        return ray.get(
            [getattr(a, get_method).remote() for a in self.actors]
        )

    def broadcast_value(self, set_method: str, value) -> None:
        import ray_tpu as ray

        ref = ray.put(value)
        ray.get(
            [getattr(a, set_method).remote(ref) for a in self.actors]
        )
