"""Ring attention: sequence-parallel exact attention over a mesh axis.

The reference has NO sequence/context parallelism (SURVEY §5.7 — its
sequence handling stops at padded chopping, ``rnn_sequencing.py:34``); this
module is the deliberate TPU-first extension: long sequences are sharded
along time over a ("sp",) mesh axis, each device holds a Q/K/V block, and
K/V blocks rotate around the ICI ring via ``lax.ppermute`` while a
flash-attention-style online softmax accumulates exact results
(Liu et al., "Ring Attention with Blockwise Transformers", 2023 —
reimplemented from the paper's math, not ported code).

Communication pattern: n-1 ppermute hops of the local K/V block — each hop
overlaps with the local block matmul, so the MXU stays busy while ICI moves
the next block.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops.flash_attention import flash_block_attention_stats

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """One (Tq, Tk) block: returns (unnormalized out, row max, row sum).

    q: (B, Tq, H, D), k/v: (B, Tk, H, D), mask: (Tq, Tk) bool or None.
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    )
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # (B, H, Tq)
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)  # (B, H, Tq)
    # f32 accumulation like the Pallas block kernel, so the XLA ring
    # (also the custom-VJP backward) computes the same function
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, m, l


def _block_attn_flash(qf, kf, vf, offset, shape, interpret):
    """The same (unnormalized out, row max, row sum) block computation
    as :func:`_block_attn`, via the fused Pallas kernel
    (``ops/flash_attention.py flash_block_attention_stats``); ``offset``
    is the runtime banded-causal bound (j <= i + offset). qf/kf/vf are
    pre-flattened (B·H, T, D) blocks — the layout transform is
    hop-invariant, so callers hoist it out of the ring scan and rotate
    the flattened K/V directly."""
    B, H = shape
    Tq, D = qf.shape[1:]
    acc, m, l = flash_block_attention_stats(
        qf, kf, vf, offset, interpret=interpret
    )
    o = acc.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)
    return o, m.reshape(B, H, Tq), l.reshape(B, H, Tq)


def _merge(o1, m1, l1, o2, m2, l2):
    """Combine two partial softmax accumulators (flash-attention merge)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    # broadcast (B,H,Tq) -> (B,Tq,H,1)
    s1 = jnp.transpose(a1, (0, 2, 1))[..., None]
    s2 = jnp.transpose(a2, (0, 2, 1))[..., None]
    o = o1 * s1 + o2 * s2
    return o, m, l


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    use_pallas: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Per-shard body; call inside shard_map over the ``axis_name`` axis.

    q/k/v: (B, T_local, H, D) — this shard's sequence block. Returns the
    attention output for the local Q block, exact w.r.t. the full
    sequence. ``use_pallas`` computes each block with the fused Pallas
    kernel (runtime banded offset, since the bound depends on the
    traced device index); the XLA block math is the default and the
    differentiable path.
    """
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]

    q_pos = my * Tq + jnp.arange(Tq)  # global positions of local Q rows
    if use_pallas:
        # flatten once; the ring rotates the flattened K/V blocks
        q = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
        k = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
        v = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)

    def hop(carry, step):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src_shard = (my - step) % n  # whose K/V block we now hold
        if use_pallas:
            # j <= i + offset ⟺ src*Tk + j <= my*Tq + i
            offset = (
                my * Tq - src_shard * Tk
                if causal
                else jnp.asarray(Tk, jnp.int32)
            )
            o, m, l = _block_attn_flash(
                q, k_cur, v_cur, offset, (B, H), interpret
            )
        else:
            if causal:
                k_pos = src_shard * Tk + jnp.arange(Tk)
                mask = k_pos[None, :] <= q_pos[:, None]
            else:
                mask = None
            o, m, l = _block_attn(q, k_cur, v_cur, mask)
        o_acc, m_acc, l_acc = _merge(o_acc, m_acc, l_acc, o, m, l)
        # rotate K/V to the next device (skip the final, unused hop
        # is harmless — keeps the scan body uniform)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, m_acc, l_acc, k_cur, v_cur), None

    o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq), jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        hop, (o0, m0, l0, k, v), jnp.arange(n)
    )
    denom = jnp.transpose(l, (0, 2, 1))[..., None]
    return (o / jnp.maximum(denom, 1e-30)).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Full-array entry point: shards (B, T, H, D) inputs along T over
    ``axis_name`` and runs the ring. T must divide by the axis size.

    ``use_pallas=None`` auto-selects the fused block kernel on TPU
    backends and the XLA block math elsewhere. The Pallas forward is
    paired with a custom VJP that differentiates through the XLA ring
    (identical math, rematerialized), so training works either way.
    On TPU the two paths agree to MXU matmul precision (~5e-3 abs for
    f32 at T≈256 — both sit that far from a float64 reference); on CPU
    they agree to ~1e-4."""
    if use_pallas is None:
        use_pallas = interpret or jax.default_backend() == "tpu"

    def run(q, k, v, pallas: bool):
        body = functools.partial(
            ring_attention_local,
            axis_name=axis_name,
            causal=causal,
            use_pallas=pallas,
            interpret=interpret,
        )
        spec = P(None, axis_name)
        fn = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            # fresh accumulators in the scan carry start axis-unvarying
            # and become varying after the first merge; skip the check
            check_vma=False,
        )
        return fn(q, k, v)

    if not use_pallas:
        return run(q, k, v, False)

    @jax.custom_vjp
    def fwd(q, k, v):
        return run(q, k, v, True)

    def fwd_rule(q, k, v):
        return run(q, k, v, True), (q, k, v)

    def bwd_rule(res, g):
        q, k, v = res
        _, vjp = jax.vjp(lambda a, b, c: run(a, b, c, False), q, k, v)
        return vjp(g)

    fwd.defvjp(fwd_rule, bwd_rule)
    return fwd(q, k, v)


def full_attention_reference(q, k, v, causal: bool = False):
    """Single-device exact attention (golden for tests)."""
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    )
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
