"""Multi-host (DCN) runtime: jax.distributed bring-up and cross-host
weight broadcast.

Plays the multi-host roles of the reference's L1 stack the TPU way:
the heavy lifting (device enumeration across hosts, ICI+DCN collective
routing) belongs to ``jax.distributed.initialize`` + XLA; this module
supplies the bring-up around it (who is the coordinator, gloo switch
for CPU harnesses, broadcast/barrier wrappers).

The KV/rendezvous control plane (KVServer/KVClient, pubsub,
heartbeats) moved to :mod:`ray_tpu.fleet.kv` in PR 17 — it belongs to
the fleet subsystem that owns the membership protocol. The names are
re-exported here for back-compat.
"""

from __future__ import annotations

import os
from typing import Optional

from ray_tpu.fleet.kv import (  # noqa: F401  (back-compat re-exports)
    HeartbeatReporter,
    KVClient,
    KVServer,
    Subscriber,
    _body_digest,
    _body_ok,
    _channel_match,
    _request_hmac,
)

# ---------------------------------------------------------------------------
# jax.distributed bring-up
# ---------------------------------------------------------------------------

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> None:
    """Join the multi-controller jax runtime (DCN). Reads
    RAY_TPU_COORDINATOR / RAY_TPU_NUM_PROCESSES / RAY_TPU_PROCESS_ID
    when args are omitted, so every host runs the same script.

    Replaces the reference's NCCL/gloo rendezvous
    (``util/collective/collective.py:120`` init_collective_group): after
    this, a global Mesh over ``jax.devices()`` spans all hosts and XLA
    routes collectives over ICI within a host/pod slice and DCN across.
    """
    global _initialized
    if _initialized:
        return
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "RAY_TPU_COORDINATOR"
    )
    if coordinator_address is None:
        return  # single-host: nothing to do
    # CPU backend: XLA's default CPU client cannot run cross-process
    # computations ("Multiprocess computations aren't implemented on
    # the CPU backend") — switch its collectives to gloo BEFORE the
    # backend initializes, so the simulated multi-host tests (and any
    # CPU-only DCN bring-up) get working psum/broadcast. TPU ignores
    # this path entirely.
    platforms = str(
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS", "")
    ).lower()
    if "cpu" in platforms:
        try:
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo"
            )
        except Exception:
            pass  # older/newer jax without the option (or gloo-less
            # jaxlib): keep the default and let init surface errors
    num_processes = int(
        num_processes
        if num_processes is not None
        else os.environ.get("RAY_TPU_NUM_PROCESSES", 1)
    )
    process_id = int(
        process_id
        if process_id is not None
        else os.environ.get("RAY_TPU_PROCESS_ID", 0)
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def global_mesh():
    """Mesh over ALL devices of ALL processes (DCN+ICI) — the same
    construction Algorithm.setup uses, so the axis naming cannot
    drift between the two paths."""
    import jax

    from ray_tpu.parallel.mesh import make_mesh

    return make_mesh(devices=jax.devices())


def broadcast_weights(tree, is_source: Optional[bool] = None):
    """Cross-host weight broadcast: every process returns process 0's
    pytree (reference WorkerSet.sync_weights across nodes / NCCL
    broadcast ``collective.py:373``). Rides XLA collectives over DCN via
    multihost_utils."""
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(
        tree, is_source=is_source
    )


def sync_global(name: str = "barrier") -> None:
    """Cross-host barrier (reference collective barrier)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
