from ray_tpu.parallel.mesh import (
    make_mesh,
    data_sharding,
    replicated,
    num_data_shards,
    DATA_AXIS,
    MODEL_AXIS,
)

__all__ = [
    "make_mesh",
    "data_sharding",
    "replicated",
    "num_data_shards",
    "DATA_AXIS",
    "MODEL_AXIS",
]
