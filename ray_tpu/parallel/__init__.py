"""Legacy parallel namespace — jax version shims plus an adapter over
the sharding runtime (``ray_tpu.sharding``). The mesh helpers re-
exported here keep the historical ``("data",)`` axis naming for the
pmap-backend learn programs; new code targets ``ray_tpu.sharding``."""

import functools

import jax

if not hasattr(jax, "shard_map"):
    # Older jax exposes shard_map only under jax.experimental (and its
    # replication checker predates the cond/scan patterns the learn
    # programs use); alias the stable name so one codebase spans both.
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    @functools.wraps(_experimental_shard_map)
    def _shard_map_compat(f, mesh=None, in_specs=None, out_specs=None, **kw):
        kw.pop("check_vma", None)
        kw.setdefault("check_rep", False)
        return _experimental_shard_map(f, mesh, in_specs, out_specs, **kw)

    jax.shard_map = _shard_map_compat

if not hasattr(jax.lax, "axis_size"):
    # same vintage gap; psum of the constant 1 folds to the static
    # axis size at trace time on these versions
    jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)

from ray_tpu.parallel.mesh import (
    make_mesh,
    data_sharding,
    replicated,
    num_data_shards,
    DATA_AXIS,
    MODEL_AXIS,
)

__all__ = [
    "make_mesh",
    "data_sharding",
    "replicated",
    "num_data_shards",
    "DATA_AXIS",
    "MODEL_AXIS",
]
