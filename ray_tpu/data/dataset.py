"""ray_tpu.data: block-based distributed Dataset.

Counterpart of the reference's ``python/ray/data/dataset.py:114``
(Dataset on Arrow blocks with a lazy ExecutionPlan —
``data/_internal/plan.py``): data lives as a list of blocks (plain
Python lists / numpy arrays); transforms are lazy stages executed
per-block as remote tasks when the dataset is consumed. Shuffle is a
single-stage scatter (the reference's push_based_shuffle collapses to
one exchange on a single host)."""

from __future__ import annotations

import builtins
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import ray_tpu as ray


def _chunk(items: Sequence, n_blocks: int) -> List[List]:
    if not items:
        return [[]]
    n = max(1, min(n_blocks, len(items)))
    size = -(-len(items) // n)
    return [
        list(items[i : i + size]) for i in range(0, len(items), size)
    ]


@ray.remote
def _apply_stages(block: List, stages) -> List:
    """All pending stages fuse into ONE task per block: no per-stage
    driver barrier or intermediate block round trips."""
    for kind, fn in stages:
        if kind == "map":
            block = [fn(x) for x in block]
        elif kind == "map_batches":
            block = list(fn(block))
        elif kind == "filter":
            block = [x for x in block if fn(x)]
        elif kind == "flat_map":
            out = []
            for x in block:
                out.extend(fn(x))
            block = out
        else:
            raise ValueError(kind)
    return block


class Dataset:
    """reference data/dataset.py:114 (lazy per-block execution)."""

    def __init__(self, blocks: List[List], stages=None):
        self._blocks = blocks
        self._stages: List = list(stages or [])

    # -- constructors -----------------------------------------------------

    @classmethod
    def from_items(
        cls, items: Sequence, parallelism: int = 4
    ) -> "Dataset":
        return cls(_chunk(list(items), parallelism))

    @classmethod
    def range(cls, n: int, parallelism: int = 4) -> "Dataset":
        return cls.from_items(list(builtins.range(n)), parallelism)

    @classmethod
    def from_numpy(
        cls, arr: np.ndarray, parallelism: int = 4
    ) -> "Dataset":
        return cls.from_items(list(arr), parallelism)

    # -- lazy transforms --------------------------------------------------

    def map(self, fn: Callable) -> "Dataset":
        return Dataset(self._blocks, self._stages + [("map", fn)])

    def map_batches(self, fn: Callable) -> "Dataset":
        """fn(list_of_rows) -> list_of_rows, applied per block."""
        return Dataset(
            self._blocks, self._stages + [("map_batches", fn)]
        )

    def filter(self, fn: Callable) -> "Dataset":
        return Dataset(self._blocks, self._stages + [("filter", fn)])

    def flat_map(self, fn: Callable) -> "Dataset":
        return Dataset(self._blocks, self._stages + [("flat_map", fn)])

    # -- execution --------------------------------------------------------

    def _materialize(self) -> List[List]:
        """Run pending stages over all blocks as parallel tasks."""
        blocks = self._blocks
        if self._stages:
            ray.init(ignore_reinit_error=True)
            refs = [
                _apply_stages.remote(b, self._stages) for b in blocks
            ]
            blocks = ray.get(refs)
            ray.free(refs)
        self._blocks = blocks
        self._stages = []
        return blocks

    # -- consumption ------------------------------------------------------

    def take(self, n: int = 20) -> List:
        out: List = []
        for b in self._materialize():
            out.extend(b)
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> List:
        out: List = []
        for b in self._materialize():
            out.extend(b)
        return out

    def count(self) -> int:
        return sum(len(b) for b in self._materialize())

    def iter_batches(self, batch_size: int = 256):
        buf: List = []
        for b in self._materialize():
            buf.extend(b)
            while len(buf) >= batch_size:
                yield buf[:batch_size]
                buf = buf[batch_size:]
        if buf:
            yield buf

    def iter_rows(self):
        for b in self._materialize():
            yield from b

    # -- reshaping --------------------------------------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        return Dataset(_chunk(self.take_all(), num_blocks))

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        rows = self.take_all()
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(rows))
        n_blocks = max(1, len(self._blocks))
        return Dataset(
            _chunk([rows[i] for i in idx], n_blocks)
        )

    def split(self, n: int) -> List["Dataset"]:
        """reference dataset.split: n equal-ish shards (Train wiring)."""
        rows = self.take_all()
        size = -(-len(rows) // n) if rows else 0
        shards = []
        for i in range(n):
            shards.append(
                Dataset([list(rows[i * size : (i + 1) * size])])
            )
        return shards

    def sort(self, key: Optional[Callable] = None) -> "Dataset":
        rows = sorted(self.take_all(), key=key)
        return Dataset(_chunk(rows, max(1, len(self._blocks))))

    def sum(self):
        return sum(self.take_all())

    def num_blocks(self) -> int:
        return len(self._blocks)

    def __repr__(self):
        return (
            f"Dataset(num_blocks={len(self._blocks)}, "
            f"pending_stages={len(self._stages)})"
        )
