"""ray_tpu.data: block-based distributed Dataset.

Counterpart of the reference's ``python/ray/data/dataset.py:114``
(Dataset on Arrow blocks with a lazy ExecutionPlan,
``data/_internal/plan.py``). Blocks are either Arrow tables (tabular
data, parquet IO, columnar batch formats) or plain Python lists
(simple rows); they live in the OBJECT PLANE as refs — the driver
routes references, workers move the bytes over shared memory —
and transforms are lazy stages fused into one task per block at
consumption time.

Shuffle and sort are DISTRIBUTED two-stage exchanges in the shape of
the reference's push-based shuffle (``_internal/push_based_shuffle.py``,
``sort.py``): stage one partitions every block (hash for shuffle,
sampled range boundaries for sort) into P parts as remote tasks; stage
two merges part (i) of every block in P parallel tasks. Row data never
gathers on the driver.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import ray_tpu as ray

try:
    import pyarrow as pa
    import pyarrow.parquet as pq
except ImportError:  # pragma: no cover - pyarrow is in the image
    pa = None
    pq = None


def _chunk(items: Sequence, n_blocks: int) -> List[List]:
    if not items:
        return [[]]
    n = max(1, min(n_blocks, len(items)))
    size = -(-len(items) // n)
    return [
        list(items[i : i + size]) for i in range(0, len(items), size)
    ]


# ---------------------------------------------------------------------------
# Block helpers (list blocks vs arrow-table blocks)
# ---------------------------------------------------------------------------


def _block_rows(block) -> List:
    if pa is not None and isinstance(block, pa.Table):
        return block.to_pylist()
    return list(block)


def _block_len(block) -> int:
    if pa is not None and isinstance(block, pa.Table):
        return block.num_rows
    return len(block)


def _rows_to_block(rows: List, like) -> Any:
    """Rebuild a block of the same family as ``like`` from rows."""
    if pa is not None and isinstance(like, pa.Table):
        if not rows:
            return like.schema.empty_table()
        return pa.Table.from_pylist(rows, schema=like.schema)
    return rows


def _concat_blocks(parts: List):
    tables = [
        p for p in parts if pa is not None and isinstance(p, pa.Table)
    ]
    if tables:
        lists = [p for p in parts if not isinstance(p, pa.Table)]
        out = pa.concat_tables(tables)
        if lists:  # mixed families: degrade to rows
            rows = out.to_pylist()
            for p in lists:
                rows.extend(p)
            return rows
        return out
    out: List = []
    for p in parts:
        out.extend(p)
    return out


def _format_batch(block, batch_format: str):
    if batch_format == "pyarrow":
        if isinstance(block, pa.Table):
            return block
        return pa.Table.from_pylist(list(block))
    if batch_format == "pandas":
        if isinstance(block, pa.Table):
            return block.to_pandas()
        import pandas as pd

        return pd.DataFrame(list(block))
    if batch_format == "numpy":
        if pa is not None and isinstance(block, pa.Table):
            return {
                name: np.asarray(col)
                for name, col in zip(
                    block.column_names, block.columns
                )
            }
        rows = list(block)
        if rows and isinstance(rows[0], dict):
            # tabular list rows → dict of column arrays (the
            # reference's numpy batch format for tabular data)
            return {
                k: np.asarray([r[k] for r in rows]) for k in rows[0]
            }
        return np.asarray(rows)
    return _block_rows(block)  # "rows" / default


def _unformat_batch(out) -> Any:
    """Whatever fn returned becomes a block again."""
    if pa is not None and isinstance(out, pa.Table):
        return out
    try:
        import pandas as pd

        if isinstance(out, pd.DataFrame):
            return pa.Table.from_pandas(out, preserve_index=False)
    except ImportError:  # pragma: no cover
        pass
    if isinstance(out, dict):  # numpy column dict
        return pa.Table.from_pydict(
            {k: np.asarray(v) for k, v in out.items()}
        )
    if isinstance(out, np.ndarray):
        return list(out)
    return list(out)


# ---------------------------------------------------------------------------
# Remote stage / shuffle tasks
# ---------------------------------------------------------------------------


@ray.remote
def _apply_stages(block, stages):
    """All pending stages fuse into ONE task per block: no per-stage
    driver barrier or intermediate block round trips."""
    for kind, fn, extra in stages:
        if kind == "read_parquet":
            block = pq.read_table(fn)  # fn = path
        elif kind == "map":
            block = _rows_to_block(
                [fn(x) for x in _block_rows(block)], block
            )
        elif kind == "map_batches":
            batch = _format_batch(block, extra or "rows")
            block = _unformat_batch(fn(batch))
        elif kind == "filter":
            block = _rows_to_block(
                [x for x in _block_rows(block) if fn(x)], block
            )
        elif kind == "flat_map":
            out = []
            for x in _block_rows(block):
                out.extend(fn(x))
            block = _rows_to_block(out, block)
        else:
            raise ValueError(kind)
    return block


def _stable_hash(value) -> int:
    """Process-stable key hash: partition tasks run in DIFFERENT
    worker processes, where python's own ``hash()`` is salted — the
    same key would land in different partitions."""
    import pickle
    import zlib

    return zlib.crc32(pickle.dumps(value))


@ray.remote
def _partition_block(block, n_parts, mode, key, bounds, seed):
    """Stage 1 of the exchange: split one block into n_parts
    (hash-random for shuffle, range for sort, stable key-hash for
    groupby)."""
    rows = _block_rows(block)
    parts: List[List] = [[] for _ in range(n_parts)]
    if mode == "shuffle":
        rng = np.random.default_rng(seed)
        assign = rng.integers(0, n_parts, len(rows))
        for row, p in zip(rows, assign):
            parts[int(p)].append(row)
    elif mode == "groupby":
        for row in rows:
            parts[_stable_hash(key(row)) % n_parts].append(row)
    else:  # range partition by sort key against sampled bounds
        for row in rows:
            k = key(row)
            p = int(np.searchsorted(bounds, k, side="right"))
            parts[p].append(row)
    return tuple(_rows_to_block(p, block) for p in parts)


@ray.remote
def _merge_parts(mode, key, seed, *parts):
    """Stage 2: merge part i of every block (sorting or reshuffling
    locally)."""
    merged = _concat_blocks(list(parts))
    rows = _block_rows(merged)
    if mode == "shuffle":
        rng = np.random.default_rng(seed)
        rows = [rows[i] for i in rng.permutation(len(rows))]
    else:
        rows.sort(key=key)
    return _rows_to_block(rows, merged)


@ray.remote
def _aggregate_parts(key, init, accumulate, finalize, out_row, *parts):
    """Groupby stage 2: every row with a given key is in exactly one
    partition (stable hash), so each task folds its groups to
    completion independently (the reference's per-partition
    GroupbyMapBlock + combine)."""
    groups: Dict = {}
    for part in parts:
        for row in _block_rows(part):
            k = key(row)
            if k not in groups:
                groups[k] = init(k)
            groups[k] = accumulate(groups[k], row)
    rows = [
        out_row(k, finalize(acc) if finalize else acc)
        for k, acc in groups.items()
    ]
    return rows


@ray.remote
def _sample_keys(block, key, k, seed):
    rows = _block_rows(block)
    if not rows:
        return []
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, len(rows), min(k, len(rows)))
    return [key(rows[int(i)]) for i in idx]


@ray.remote
def _write_parquet_block(block, path):
    if not (pa is not None and isinstance(block, pa.Table)):
        block = pa.Table.from_pylist(_block_rows(block))
    pq.write_table(block, path)
    return path


@ray.remote
def _block_count(block) -> int:
    return _block_len(block)


@ray.remote
def _gather_spans(spans, *blocks):
    """Concatenate row ranges of several blocks: ``spans[i]`` is the
    (start, stop) slice of ``blocks[i]``. The workhorse of the
    block-wise reshapes (repartition/split/zip) — row data moves
    worker↔worker through the object plane, never the driver."""
    return _rows_to_block(
        _span_rows(spans, blocks), blocks[0] if blocks else None
    )


def _span_rows(spans, blocks) -> List:
    """Rows of the (start, stop) ranges of several blocks, in order —
    shared by the span-gather remote helpers."""
    rows: List = []
    for (start, stop), b in builtins.zip(spans, blocks):
        rows.extend(_block_rows(b)[start:stop])
    return rows


@ray.remote
def _zip_blocks(a_block, spans, *b_blocks):
    """Pair one left block with the right-hand row ranges covering the
    same global positions (reference dataset.zip's block-aligned
    implementation, dataset.py:1403 area)."""
    return list(
        builtins.zip(_block_rows(a_block), _span_rows(spans, b_blocks))
    )


def _cover_spans(pos: int, n: int, offsets, refs):
    """The (start, stop) ranges + their block refs covering global
    rows [pos, pos+n), ready to splat into a span-gather task."""
    spans, span_refs = [], []
    for j in range(len(offsets) - 1):
        s, e = int(offsets[j]), int(offsets[j + 1])
        lo, hi = max(pos, s), min(pos + n, e)
        if lo < hi:
            spans.append((lo - s, hi - s))
            span_refs.append(refs[j])
    return spans, span_refs


class Dataset:
    """reference data/dataset.py:114 (lazy per-block execution)."""

    def __init__(self, blocks: List, stages=None, *, refs=None):
        # blocks may be in-memory values or object refs; they are
        # normalized to refs on first execution
        self._blocks = blocks
        self._refs = refs  # List[ObjectRef] once normalized
        self._stages: List = list(stages or [])
        self._per_block_stages = None  # read_parquet per-path stages

    # -- constructors -----------------------------------------------------

    @classmethod
    def from_items(
        cls, items: Sequence, parallelism: int = 4
    ) -> "Dataset":
        return cls(_chunk(list(items), parallelism))

    @classmethod
    def range(cls, n: int, parallelism: int = 4) -> "Dataset":
        return cls.from_items(list(builtins.range(n)), parallelism)

    @classmethod
    def from_numpy(
        cls, arr: np.ndarray, parallelism: int = 4
    ) -> "Dataset":
        return cls.from_items(list(arr), parallelism)

    @classmethod
    def from_arrow(cls, tables) -> "Dataset":
        if pa is not None and isinstance(tables, pa.Table):
            tables = [tables]
        return cls(list(tables))

    @classmethod
    def from_pandas(cls, dfs) -> "Dataset":
        import pandas as pd

        if isinstance(dfs, pd.DataFrame):
            dfs = [dfs]
        return cls(
            [
                pa.Table.from_pandas(df, preserve_index=False)
                for df in dfs
            ]
        )

    @classmethod
    def read_parquet(cls, paths) -> "Dataset":
        """One block per file, read INSIDE the tasks (lazy — the
        driver never holds the file bytes; reference
        data/read_api.py read_parquet)."""
        import glob as _glob
        import os

        if isinstance(paths, str):
            if os.path.isdir(paths):
                paths = sorted(
                    _glob.glob(os.path.join(paths, "*.parquet"))
                )
            else:
                paths = sorted(_glob.glob(paths)) or [paths]
        ds = cls([None] * len(paths))
        # each block gets its own read stage: blocks are per-path
        ds._per_block_stages = [
            [("read_parquet", p, None)] for p in paths
        ]
        return ds

    # -- lazy transforms --------------------------------------------------

    def _with_stage(self, stage) -> "Dataset":
        out = Dataset(
            self._blocks, self._stages + [stage], refs=self._refs
        )
        out._per_block_stages = getattr(
            self, "_per_block_stages", None
        )
        return out

    def map(self, fn: Callable) -> "Dataset":
        return self._with_stage(("map", fn, None))

    def map_batches(
        self, fn: Callable, batch_format: str = "rows"
    ) -> "Dataset":
        """fn(batch) -> batch per block; batch_format selects the
        in-task representation: "rows" (list), "pyarrow" (Table),
        "pandas" (DataFrame), "numpy" (dict of columns / array)
        (reference dataset.map_batches batch_format)."""
        return self._with_stage(("map_batches", fn, batch_format))

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_stage(("filter", fn, None))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with_stage(("flat_map", fn, None))

    # -- execution --------------------------------------------------------

    def _materialize_refs(self) -> List:
        """→ one object ref per fully-transformed block; stages and
        per-block read stages execute as parallel tasks."""
        ray.init(ignore_reinit_error=True)
        per_block = getattr(self, "_per_block_stages", None)
        if self._refs is None:
            if per_block is not None:
                refs = [
                    _apply_stages.remote(
                        None, pb + self._stages
                    )
                    for pb in per_block
                ]
            elif self._stages:
                refs = [
                    _apply_stages.remote(b, self._stages)
                    for b in self._blocks
                ]
            else:
                refs = [ray.put(b) for b in self._blocks]
        elif self._stages:
            refs = [
                _apply_stages.remote(r, self._stages)
                for r in self._refs
            ]
        else:
            refs = self._refs
        self._refs = refs
        self._per_block_stages = None
        self._stages = []
        return refs

    def _ref_counts(self):
        """(refs, per-block row counts), counts cached per refs list
        (reshapes re-count the same materialized refs otherwise)."""
        refs = self._materialize_refs()
        cached = getattr(self, "_block_counts", None)
        if cached is not None and cached[0] is refs:
            return refs, cached[1]
        counts = ray.get([_block_count.remote(r) for r in refs])
        self._block_counts = (refs, counts)
        return refs, counts

    def _materialize(self) -> List:
        """Blocks as in-memory values (driver-side consumption)."""
        blocks = ray.get(self._materialize_refs())
        return blocks

    # -- consumption ------------------------------------------------------

    def take(self, n: int = 20) -> List:
        out: List = []
        for ref in self._materialize_refs():
            out.extend(_block_rows(ray.get(ref)))
            if len(out) >= n:
                return out[:n]
        return out

    def take_all(self) -> List:
        out: List = []
        for b in self._materialize():
            out.extend(_block_rows(b))
        return out

    def count(self) -> int:
        refs = self._materialize_refs()
        counts = ray.get(
            [_block_count.remote(r) for r in refs]
        )
        return sum(counts)

    def iter_batches(
        self, batch_size: int = 256, batch_format: str = "rows"
    ):
        buf: List = []
        for ref in self._materialize_refs():
            buf.extend(_block_rows(ray.get(ref)))
            while len(buf) >= batch_size:
                yield _maybe_format_rows(
                    buf[:batch_size], batch_format
                )
                buf = buf[batch_size:]
        if buf:
            yield _maybe_format_rows(buf, batch_format)

    def iter_torch_batches(self, batch_size: int = 256):
        """Batches as dicts of torch CPU tensors (reference
        dataset.iter_torch_batches; tabular rows only)."""
        import torch

        for batch in self.iter_batches(batch_size, "numpy"):
            yield {
                k: torch.as_tensor(v) for k, v in batch.items()
            }

    def iter_rows(self):
        for ref in self._materialize_refs():
            yield from _block_rows(ray.get(ref))

    def to_pandas(self):
        import pandas as pd

        blocks = self._materialize()
        frames = [
            b.to_pandas()
            if pa is not None and isinstance(b, pa.Table)
            else pd.DataFrame(_block_rows(b))
            for b in blocks
        ]
        return pd.concat(frames, ignore_index=True)

    def write_parquet(self, dir_path: str) -> List[str]:
        """Per-block parallel parquet writes (reference
        dataset.write_parquet)."""
        import os

        os.makedirs(dir_path, exist_ok=True)
        refs = self._materialize_refs()
        return ray.get(
            [
                _write_parquet_block.remote(
                    r, os.path.join(dir_path, f"block_{i:05d}.parquet")
                )
                for i, r in enumerate(refs)
            ]
        )

    # -- reshaping (distributed exchanges) --------------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rechunk into ``num_blocks`` blocks WITHOUT materializing on
        the driver: each output block is a span-gather task over the
        input refs (the driver routes counts and refs only)."""
        refs, counts = self._ref_counts()
        total = sum(counts)
        offsets = np.cumsum([0] + counts)
        num_blocks = max(1, num_blocks)
        size = -(-total // num_blocks) if total else 0
        out_refs = []
        for i in range(num_blocks):
            pos = i * size
            n = min(size, total - pos)
            if n <= 0:
                break
            spans, span_refs = _cover_spans(pos, n, offsets, refs)
            out_refs.append(_gather_spans.remote(spans, *span_refs))
        return Dataset(None, refs=out_refs or [ray.put([])])

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Two-stage distributed exchange (the push_based_shuffle
        shape): partition tasks fan rows out by hash, merge tasks
        reassemble — the driver only routes refs."""
        refs = self._materialize_refs()
        n = max(1, len(refs))
        # unseeded shuffles must differ per call (fresh OS entropy);
        # seeded ones stay deterministic
        base = (
            int(seed)
            if seed is not None
            else int(np.random.SeedSequence().entropy % (2**31))
        )
        if n == 1:
            rows = self.take_all()
            rng = np.random.default_rng(seed)
            return Dataset(
                [[rows[i] for i in rng.permutation(len(rows))]]
            )
        part_refs = [
            _partition_block.options(num_returns=n).remote(
                r, n, "shuffle", None, None, base + 1000 + i
            )
            for i, r in enumerate(refs)
        ]
        merged = [
            _merge_parts.remote(
                "shuffle",
                None,
                base + 2000 + j,
                *[parts[j] for parts in part_refs],
            )
            for j in range(n)
        ]
        _free_when_done(
            [p for parts in part_refs for p in parts], merged
        )
        return Dataset(None, refs=merged)

    def sort(self, key: Optional[Callable] = None) -> "Dataset":
        """Distributed range-partition sort (reference
        _internal/sort.py): sample keys → boundary quantiles →
        partition tasks → per-range merge-sort tasks."""
        key = key or (lambda x: x)
        refs = self._materialize_refs()
        n = max(1, len(refs))
        if n == 1:
            rows = sorted(self.take_all(), key=key)
            return Dataset([rows])
        samples: List = []
        for s in ray.get(
            [
                _sample_keys.remote(r, key, 32, i)
                for i, r in enumerate(refs)
            ]
        ):
            samples.extend(s)
        samples.sort()
        if not samples:
            return Dataset([[]])
        bounds = [
            samples[int(len(samples) * (j + 1) / n)]
            for j in range(n - 1)
        ]
        part_refs = [
            _partition_block.options(num_returns=n).remote(
                r, n, "sort", key, bounds, 0
            )
            for r in refs
        ]
        merged = [
            _merge_parts.remote(
                "sort", key, 0, *[parts[j] for parts in part_refs]
            )
            for j in range(n)
        ]
        _free_when_done(
            [p for parts in part_refs for p in parts], merged
        )
        return Dataset(None, refs=merged)

    def split(self, n: int) -> List["Dataset"]:
        """reference dataset.split: n equal-ish shards (Train wiring),
        block-wise — each shard is a span-gather ref, so rows move
        worker-to-worker, not through the driver."""
        refs, counts = self._ref_counts()
        total = sum(counts)
        offsets = np.cumsum([0] + counts)
        size = -(-total // n) if total else 0
        shards = []
        for i in range(n):
            pos = i * size
            m = max(0, min(size, total - pos))
            if m <= 0:
                shards.append(Dataset([[]]))
                continue
            spans, span_refs = _cover_spans(pos, m, offsets, refs)
            shards.append(
                Dataset(
                    None,
                    refs=[_gather_spans.remote(spans, *span_refs)],
                )
            )
        return shards

    def sum(self):
        return sum(self.take_all())

    # -- relational ops (reference dataset.py groupby/union/zip) --------

    def groupby(self, key) -> "GroupedDataset":
        """Group rows by a column name (dict rows) or a key callable;
        aggregations run as a distributed hash exchange (reference
        dataset.py groupby + grouped_data.py)."""
        return GroupedDataset(self, key)

    def unique(self, key=None) -> List:
        """Distinct keys (reference dataset.unique), via the groupby
        exchange."""
        grouped = self.groupby(key)
        kn = grouped._key_name
        return [r[kn] for r in grouped.count().take_all()]

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets WITHOUT materializing rows on the
        driver — block refs are simply chained (reference
        dataset.union)."""
        refs = list(self._materialize_refs())
        for o in others:
            refs.extend(o._materialize_refs())
        return Dataset(None, refs=refs)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-wise zip of two same-length datasets into (row_a,
        row_b) tuples (reference dataset.zip, scoped to tuple rows).
        Block-wise: output blocks follow the left partitioning; each
        is a remote task pairing a left block with the right-hand row
        spans at the same global positions — no driver
        materialization."""
        a_refs, a_counts = self._ref_counts()
        b_refs, b_counts = other._ref_counts()
        if sum(a_counts) != sum(b_counts):
            raise ValueError(
                f"zip needs equal lengths, got {sum(a_counts)} vs "
                f"{sum(b_counts)}"
            )
        b_offsets = np.cumsum([0] + b_counts)
        out_refs = []
        pos = 0
        for aref, n in builtins.zip(a_refs, a_counts):
            spans, span_refs = _cover_spans(pos, n, b_offsets, b_refs)
            out_refs.append(
                _zip_blocks.remote(aref, spans, *span_refs)
            )
            pos += n
        return Dataset(None, refs=out_refs or [ray.put([])])

    def num_blocks(self) -> int:
        if self._refs is not None:
            return len(self._refs)
        per_block = getattr(self, "_per_block_stages", None)
        if per_block is not None:
            return len(per_block)
        return len(self._blocks)

    def schema(self):
        refs = self._materialize_refs()
        first = ray.get(refs[0]) if refs else None
        if pa is not None and isinstance(first, pa.Table):
            return first.schema
        return type(first[0]) if first else None

    def __repr__(self):
        return (
            f"Dataset(num_blocks={self.num_blocks()}, "
            f"pending_stages={len(self._stages)})"
        )


def _key_fn(key):
    if key is None:
        return lambda r: r
    if callable(key):
        return key
    return lambda r, _k=key: r[_k]


class GroupedDataset:
    """reference ``data/grouped_data.py GroupedData``: aggregations
    over a distributed hash exchange. Every key lands in exactly one
    partition task (stable hash), so folds complete independently —
    the driver never sees row data, only the per-group result rows."""

    def __init__(self, ds: "Dataset", key):
        self._ds = ds
        self._key = _key_fn(key)
        self._key_name = key if isinstance(key, str) else "key"

    def aggregate(
        self,
        init: Callable,
        accumulate: Callable,
        finalize: Optional[Callable] = None,
        name: str = "agg",
    ) -> "Dataset":
        """Generic fold (reference AggregateFn): ``init(key) -> acc``,
        ``accumulate(acc, row) -> acc``, optional ``finalize(acc)``.
        Returns a Dataset of ``{<key_name>: key, <name>: value}``
        rows."""
        kn, nm = self._key_name, name

        def out_row(k, v):
            return {kn: k, nm: v}

        refs = self._ds._materialize_refs()
        n = max(1, len(refs))
        if n == 1:
            rows = ray.get(
                _aggregate_parts.remote(
                    self._key, init, accumulate, finalize, out_row,
                    *refs,
                )
            )
            return Dataset([rows])
        part_refs = [
            _partition_block.options(num_returns=n).remote(
                r, n, "groupby", self._key, None, 0
            )
            for r in refs
        ]
        agg = [
            _aggregate_parts.remote(
                self._key, init, accumulate, finalize, out_row,
                *[parts[j] for parts in part_refs],
            )
            for j in range(n)
        ]
        _free_when_done(
            [p for parts in part_refs for p in parts], agg
        )
        return Dataset(None, refs=agg)

    def count(self) -> "Dataset":
        return self.aggregate(
            lambda k: 0, lambda a, r: a + 1, name="count()"
        )

    def sum(self, on=None) -> "Dataset":
        v = _key_fn(on)
        return self.aggregate(
            lambda k: 0, lambda a, r: a + v(r), name=f"sum({on})"
        )

    def min(self, on=None) -> "Dataset":
        v = _key_fn(on)
        return self.aggregate(
            lambda k: None,
            lambda a, r: v(r) if a is None else min(a, v(r)),
            name=f"min({on})",
        )

    def max(self, on=None) -> "Dataset":
        v = _key_fn(on)
        return self.aggregate(
            lambda k: None,
            lambda a, r: v(r) if a is None else max(a, v(r)),
            name=f"max({on})",
        )

    def mean(self, on=None) -> "Dataset":
        v = _key_fn(on)
        return self.aggregate(
            lambda k: (0.0, 0),
            lambda a, r: (a[0] + v(r), a[1] + 1),
            finalize=lambda a: a[0] / a[1],
            name=f"mean({on})",
        )

    def map_groups(self, fn: Callable) -> "Dataset":
        """Apply ``fn(rows) -> rows`` per group (reference
        map_groups), riding the same exchange."""
        collected = self.aggregate(
            lambda k: [],
            lambda a, r: a + [r],
            name="rows",
        )
        return collected.flat_map(lambda row: fn(row["rows"]))


def _maybe_format_rows(rows: List, batch_format: str):
    if batch_format == "rows":
        return rows
    return _format_batch(rows, batch_format)


def _free_when_done(dep_refs: List, out_refs: List) -> None:
    """Free intermediate refs (exchange partitions) once every output
    consuming them is ready — without this, shuffle/sort would pin n*n
    partition blocks in the object store until driver shutdown (the
    reference's refcounting handles this; here lifetimes are explicit,
    DISPOSITIONS single-owner posture)."""
    remaining = {"n": len(out_refs)}
    lock = __import__("threading").Lock()

    def on_one_done():
        with lock:
            remaining["n"] -= 1
            done = remaining["n"] == 0
        if done:
            try:
                ray.free(dep_refs)
            except Exception:
                pass

    for ref in out_refs:
        ref._store.on_ready(ref.id, on_one_done)
