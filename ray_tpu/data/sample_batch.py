"""Columnar trajectory containers.

TPU-native counterpart of the reference's ``rllib/policy/sample_batch.py:30``
(SampleBatch) and ``:1028`` (MultiAgentBatch). A SampleBatch is a dict of
equal-length numpy arrays on the host; it converts losslessly to a JAX pytree
(``to_device``) so a whole batch can be fed to a jitted learner step in one
transfer. All mutation happens on host numpy; on-device data is immutable.

Design differences from the reference (deliberate, TPU-first):
  - No lazy compression codecs in the hot path; batches move through the
    shared-memory object plane zero-copy instead.
  - ``right_zero_pad`` / ``timeslices`` always produce *static* shapes: TPU
    compilation caches require fixed (B, T).
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

# Column name constants (parity with reference sample_batch.py:60-117).
OBS = "obs"
NEXT_OBS = "new_obs"
ACTIONS = "actions"
REWARDS = "rewards"
PREV_ACTIONS = "prev_actions"
PREV_REWARDS = "prev_rewards"
TERMINATEDS = "dones"
TRUNCATEDS = "truncateds"
INFOS = "infos"
EPS_ID = "eps_id"
UNROLL_ID = "unroll_id"
AGENT_INDEX = "agent_index"
T = "t"
ACTION_DIST_INPUTS = "action_dist_inputs"
ACTION_LOGP = "action_logp"
ACTION_PROB = "action_prob"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
SEQ_LENS = "seq_lens"
STATE_IN_PREFIX = "state_in_"
STATE_OUT_PREFIX = "state_out_"


from ray_tpu.ops.framestack import FRAMES as _FRAME_POOL


def _is_array_col(key: str) -> bool:
    # the frame POOL (ops/framestack) is not a per-row column — its
    # length is rows + stack_k - 1 by design
    return key not in (SEQ_LENS, _FRAME_POOL)


def _reject_frame_pool(batch, op: str) -> None:
    """Row transforms (slice/shuffle/...) cannot preserve pool/index
    consistency; the frame-pool format is a TRANSFER format (built
    worker-side by ``compress_for_shipping`` or learner-side before
    ``learn_on_batch``), not a storage format. ``concat_samples`` is
    the one supported transform (pool merge + index offset). Fail
    loudly instead of silently dropping the pool."""
    if _FRAME_POOL in batch:
        raise ValueError(
            f"SampleBatch.{op} does not support the deduplicated "
            f"frame-pool format ({_FRAME_POOL!r}); materialize stacked "
            "observations first (ops/framestack.build_stacks) or "
            "apply the transform before decomposing"
        )


class SampleBatch(dict):
    """A dict of numpy arrays with equal leading dimension ("count").

    Reference parity: ``rllib/policy/sample_batch.py:30``.
    """

    # Re-export constants as class attributes for RLlib-style access
    # (SampleBatch.OBS etc).
    OBS = OBS
    NEXT_OBS = NEXT_OBS
    ACTIONS = ACTIONS
    REWARDS = REWARDS
    PREV_ACTIONS = PREV_ACTIONS
    PREV_REWARDS = PREV_REWARDS
    TERMINATEDS = TERMINATEDS
    DONES = TERMINATEDS
    TRUNCATEDS = TRUNCATEDS
    INFOS = INFOS
    EPS_ID = EPS_ID
    UNROLL_ID = UNROLL_ID
    AGENT_INDEX = AGENT_INDEX
    T = T
    ACTION_DIST_INPUTS = ACTION_DIST_INPUTS
    ACTION_LOGP = ACTION_LOGP
    ACTION_PROB = ACTION_PROB
    VF_PREDS = VF_PREDS
    ADVANTAGES = ADVANTAGES
    VALUE_TARGETS = VALUE_TARGETS
    SEQ_LENS = SEQ_LENS

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if isinstance(v, (list, tuple)) and k != INFOS:
                self[k] = np.asarray(v)
        lengths = {
            k: len(v)
            for k, v in self.items()
            if _is_array_col(k) and hasattr(v, "__len__")
        }
        if lengths:
            counts = set(lengths.values())
            if len(counts) != 1:
                raise ValueError(
                    f"All columns must have equal length, got {lengths}"
                )
            self.count = counts.pop()
        else:
            self.count = 0

    # -- Basic info ------------------------------------------------------

    def __len__(self) -> int:
        return self.count

    @property
    def agent_steps(self) -> int:
        return self.count

    @property
    def env_steps_(self) -> int:
        return self.count

    def env_steps(self) -> int:
        return self.count

    def size_bytes(self) -> int:
        return sum(
            v.nbytes for v in self.values() if isinstance(v, np.ndarray)
        )

    # -- Transformations --------------------------------------------------

    def copy(self, shallow: bool = False) -> "SampleBatch":
        if shallow:
            return SampleBatch({k: v for k, v in self.items()})
        return SampleBatch(
            {
                k: (v.copy() if isinstance(v, np.ndarray) else v)
                for k, v in self.items()
            }
        )

    def rows(self) -> Iterator[Dict[str, np.ndarray]]:
        for i in range(self.count):
            yield {k: v[i] for k, v in self.items() if _is_array_col(k)}

    def columns(self, keys: Sequence[str]) -> List[np.ndarray]:
        return [self[k] for k in keys]

    def slice(self, start: int, end: int) -> "SampleBatch":
        """Row-slice [start, end) of every column (reference :407)."""
        _reject_frame_pool(self, "slice")
        return SampleBatch(
            {k: v[start:end] for k, v in self.items() if _is_array_col(k)}
        )

    def __getitem__(self, key):
        if isinstance(key, slice):
            return self.slice(
                key.start or 0, key.stop if key.stop is not None else self.count
            )
        return super().__getitem__(key)

    def select(self, keys: Sequence[str]) -> "SampleBatch":
        return SampleBatch({k: self[k] for k in keys if k in self})

    def shuffle(self, rng: Optional[np.random.Generator] = None) -> "SampleBatch":
        """In-place row permutation (reference :317)."""
        rng = rng or np.random.default_rng()
        perm = rng.permutation(self.count)
        for k, v in self.items():
            if _is_array_col(k) and isinstance(v, np.ndarray):
                self[k] = v[perm]
        return self

    def timeslices(self, size: int) -> List["SampleBatch"]:
        """Chop into fixed-size row slices (reference :478). The final
        partial slice is dropped to keep static shapes for TPU."""
        return [
            self.slice(i, i + size)
            for i in range(0, self.count - size + 1, size)
        ]

    def minibatches(
        self, minibatch_size: int, num_epochs: int = 1,
        rng: Optional[np.random.Generator] = None,
    ) -> Iterator["SampleBatch"]:
        """Yield shuffled fixed-size minibatches for SGD epochs."""
        rng = rng or np.random.default_rng()
        for _ in range(num_epochs):
            perm = rng.permutation(self.count)
            for i in range(0, self.count - minibatch_size + 1, minibatch_size):
                idx = perm[i : i + minibatch_size]
                yield SampleBatch(
                    {
                        k: v[idx]
                        for k, v in self.items()
                        if _is_array_col(k) and isinstance(v, np.ndarray)
                    }
                )

    def right_zero_pad(self, max_len: int) -> "SampleBatch":
        """Pad every column's leading dim up to a multiple handling
        (reference :536). Produces exactly ``max_len`` rows."""
        if self.count > max_len:
            raise ValueError(f"count {self.count} > max_len {max_len}")
        pad = max_len - self.count
        out = {}
        for k, v in self.items():
            if _is_array_col(k) and isinstance(v, np.ndarray):
                pad_width = [(0, pad)] + [(0, 0)] * (v.ndim - 1)
                out[k] = np.pad(v, pad_width)
        sb = SampleBatch(out)
        sb[SEQ_LENS] = np.array([self.count], dtype=np.int32)
        return sb

    def split_by_episode(self) -> List["SampleBatch"]:
        """Split along EPS_ID boundaries (reference :350)."""
        if EPS_ID not in self:
            return [self]
        eps = np.asarray(self[EPS_ID])
        boundaries = np.where(eps[1:] != eps[:-1])[0] + 1
        starts = [0] + boundaries.tolist() + [self.count]
        return [
            self.slice(starts[i], starts[i + 1])
            for i in range(len(starts) - 1)
        ]

    def to_device(self, sharding=None):
        """Move all array columns to accelerator as one pytree transfer."""
        import jax

        arrs = {
            k: v for k, v in self.items()
            if isinstance(v, np.ndarray) and v.dtype != object
        }
        if sharding is not None:
            return jax.device_put(arrs, sharding)
        return jax.device_put(arrs)

    def as_multi_agent(self) -> "MultiAgentBatch":
        return MultiAgentBatch({DEFAULT_POLICY_ID: self}, self.count)

    def __repr__(self):
        return f"SampleBatch({self.count}: {list(self.keys())})"


DEFAULT_POLICY_ID = "default_policy"


def _concat_arrays(vals: List[np.ndarray]) -> np.ndarray:
    """Row-concat with a preallocated output for uniform-dtype columns.

    This concat sits on the sampling pipeline's critical path (the
    prefetch thread assembles train batches from rollout fragments while
    the SGD nest runs), so it avoids the generic ``np.concatenate``
    dtype-promotion machinery: one ``np.empty`` of the final column and
    a single-copy assemble. Mixed dtypes/shapes fall through to numpy's
    promotion rules unchanged."""
    if len(vals) == 1:
        # still a copy: fragments can be read-only views of the shm
        # object plane, and concat output has always been writable
        return vals[0].copy()
    first = vals[0]
    dtype, trail = first.dtype, first.shape[1:]
    if any(
        v.dtype != dtype or v.shape[1:] != trail for v in vals[1:]
    ):
        return np.concatenate(vals, axis=0)
    total = sum(v.shape[0] for v in vals)
    out = np.empty((total,) + trail, dtype)
    pos = 0
    for v in vals:
        n = v.shape[0]
        out[pos : pos + n] = v
        pos += n
    return out


def concat_samples(
    batches: Sequence[Union[SampleBatch, "MultiAgentBatch"]]
) -> Union[SampleBatch, "MultiAgentBatch"]:
    """Concatenate row-wise (reference module-level concat_samples :1245)."""
    if not batches:
        return SampleBatch()
    if isinstance(batches[0], MultiAgentBatch):
        return MultiAgentBatch.concat_samples(list(batches))
    from ray_tpu.ops.framestack import FRAME_IDX as _FRAME_IDX

    pooled = [_FRAME_POOL in b for b in batches]
    if any(pooled) and not all(pooled):
        # compression is per-fragment and data-dependent (the sliding
        # window verification can fail on one fragment and pass on its
        # siblings), so mixed inputs must degrade to stacks — losing
        # the dedup win, never correctness
        from ray_tpu.ops.framestack import materialize_fragment

        # stack depth comes from a stacked sibling's obs channel dim
        # (the mixed case guarantees one exists)
        stack_k = next(
            int(np.asarray(b[OBS]).shape[-1])
            for b in batches
            if _FRAME_POOL not in b and OBS in b
        )
        batches = [
            SampleBatch(materialize_fragment(dict(b), stack_k))
            if _FRAME_POOL in b
            else b
            for b in batches
        ]
        pooled = [False] * len(batches)
    if any(pooled):
        # frame-pool batches concatenate by merging pools and
        # offsetting each batch's first-frame indices — this keeps
        # worker-side compressed fragments compressed through the
        # driver concat (no re-materialization of stacks)
        out = {}
        pools = [np.asarray(b[_FRAME_POOL]) for b in batches]
        offsets = np.cumsum([0] + [len(p) for p in pools[:-1]])
        out[_FRAME_POOL] = _concat_arrays(pools)
        # offset-add straight into the preallocated index column (the
        # per-batch `idx + off` temporaries were a copy each)
        idxs = [np.asarray(b[_FRAME_IDX], np.int32) for b in batches]
        idx_out = np.empty(sum(len(i) for i in idxs), np.int32)
        pos = 0
        for v, off in zip(idxs, offsets):
            np.add(v, np.int32(off), out=idx_out[pos : pos + len(v)])
            pos += len(v)
        out[_FRAME_IDX] = idx_out
        keys = [
            k
            for k in batches[0].keys()
            if k not in (_FRAME_POOL, _FRAME_IDX)
        ]
    else:
        out = {}
        keys = batches[0].keys()
    for k in keys:
        if not _is_array_col(k):
            continue
        vals = [b[k] for b in batches if k in b]
        if vals and isinstance(vals[0], np.ndarray):
            out[k] = _concat_arrays(vals)
        else:
            out[k] = list(itertools.chain.from_iterable(vals))
    return SampleBatch(out)


class MultiAgentBatch:
    """Maps policy id -> SampleBatch (reference sample_batch.py:1028)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch], env_steps: int):
        self.policy_batches = policy_batches
        self.count = env_steps

    def env_steps(self) -> int:
        return self.count

    def agent_steps(self) -> int:
        return sum(b.count for b in self.policy_batches.values())

    def size_bytes(self) -> int:
        return sum(b.size_bytes() for b in self.policy_batches.values())

    def timeslices(self, size: int) -> List["MultiAgentBatch"]:
        out = []
        slices = {
            pid: b.timeslices(size) for pid, b in self.policy_batches.items()
        }
        n = min(len(s) for s in slices.values()) if slices else 0
        for i in range(n):
            out.append(
                MultiAgentBatch(
                    {pid: s[i] for pid, s in slices.items()}, size
                )
            )
        return out

    @staticmethod
    def concat_samples(batches: List["MultiAgentBatch"]) -> "MultiAgentBatch":
        policy_batches: Dict[str, List[SampleBatch]] = {}
        env_steps = 0
        for b in batches:
            if isinstance(b, SampleBatch):
                b = b.as_multi_agent()
            env_steps += b.env_steps()
            for pid, sb in b.policy_batches.items():
                policy_batches.setdefault(pid, []).append(sb)
        return MultiAgentBatch(
            {pid: concat_samples(sbs) for pid, sbs in policy_batches.items()},
            env_steps,
        )

    @staticmethod
    def wrap_as_needed(
        policy_batches: Dict[str, SampleBatch], env_steps: int
    ) -> Union[SampleBatch, "MultiAgentBatch"]:
        if len(policy_batches) == 1 and DEFAULT_POLICY_ID in policy_batches:
            return policy_batches[DEFAULT_POLICY_ID]
        return MultiAgentBatch(policy_batches, env_steps)

    def copy(self) -> "MultiAgentBatch":
        return MultiAgentBatch(
            {pid: b.copy() for pid, b in self.policy_batches.items()},
            self.count,
        )

    def __repr__(self):
        return f"MultiAgentBatch({self.count}: {list(self.policy_batches)})"
