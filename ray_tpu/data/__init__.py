from ray_tpu.data.dataset import Dataset
from ray_tpu.data.sample_batch import (
    MultiAgentBatch,
    SampleBatch,
    concat_samples,
)

from_items = Dataset.from_items
range = Dataset.range  # noqa: A001 — reference ray.data.range
from_numpy = Dataset.from_numpy

__all__ = [
    "SampleBatch",
    "MultiAgentBatch",
    "concat_samples",
    "Dataset",
    "from_items",
    "range",
    "from_numpy",
]
