from ray_tpu.data.sample_batch import SampleBatch, MultiAgentBatch, concat_samples

__all__ = ["SampleBatch", "MultiAgentBatch", "concat_samples"]
