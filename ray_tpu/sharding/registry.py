"""ray_tpu.sharding.registry — the compiled-program registry.

Every executable an AlgorithmConfig lowers — learn nests, superstep
bodies, the device rollout engine, replay insert/sample/draw programs,
prioritized-tree programs, serve buckets — carries a ``sharded_jit``
label (the same label the compile-cache stats and the PR-13 device
ledger report). This module makes that inventory a first-class object:
a :class:`ProgramRegistry` of :class:`ProgramSpec` rows, predicted
up-front from the config rather than discovered after the fact, so AOT
pre-seeding, warmup sweeps and dispatch-diet coverage checks are all
ONE walk over the same list.

Three consumers (docs/API.md "program registry"):

- ``Algorithm.setup`` builds ``algo.program_registry`` via
  :func:`for_algorithm` and, when ``config["aot_cache_dir"]`` is set,
  sweeps the warmable specs so a restarted driver pre-seeds its
  executables before the first train call;
- ``serve.BatchedPolicyServer.warmup`` walks its per-bucket specs
  (registered by the server itself) instead of an ad-hoc loop;
- ``tests/test_dispatch_diet.py`` asserts completeness: every label
  ``compile_stats()`` observed after a run matches some spec — a new
  program that forgets to register here fails CI, which is what keeps
  the warmup/AOT sweep exhaustive.

Labels with data-dependent components (batch sizes resolved at the
first learn call, draw widths, bucket sizes) register as anchored
regexes; fully static labels register exact. Specs may carry a
zero-arg ``warm`` callable — build + lower the program without
dispatching — which is what the sweep runs.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional


@dataclasses.dataclass
class ProgramSpec:
    """One predicted executable: an exact label or an anchored regex
    over the ``sharded_jit`` label space, plus where it comes from and
    (optionally) how to warm it ahead of first dispatch."""

    label: str
    kind: str = "other"  # learn | superstep | rollout | replay | tree | serve | grads | stack | other
    policy_id: str = ""
    regex: bool = False
    warm: Optional[Callable[[], Any]] = None
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self._pattern = (
            re.compile(self.label) if self.regex else None
        )

    def matches(self, label: str) -> bool:
        if self._pattern is not None:
            return self._pattern.fullmatch(label) is not None
        return label == self.label

    def describe(self) -> Dict[str, Any]:
        return {
            "label": self.label,
            "kind": self.kind,
            "policy_id": self.policy_id,
            "regex": self.regex,
            "warmable": self.warm is not None,
            **({"meta": dict(self.meta)} if self.meta else {}),
        }


class ProgramRegistry:
    """The mutable spec list + the sweeps over it. Built once on the
    driver (Algorithm.setup / server init) and only read afterwards;
    the lock covers late additions (a server attaching its buckets to
    an algorithm's registry)."""

    # ray-tpu: thread=driver

    def __init__(self) -> None:
        self._specs: List[ProgramSpec] = []
        self._lock = threading.Lock()

    # -- building -------------------------------------------------------

    def add(self, spec: ProgramSpec) -> ProgramSpec:
        with self._lock:
            self._specs.append(spec)
        return spec

    def add_program(self, label: str, **kwargs) -> ProgramSpec:
        return self.add(ProgramSpec(label=label, **kwargs))

    def extend(self, specs) -> None:
        with self._lock:
            self._specs.extend(specs)

    # -- reading --------------------------------------------------------

    def __len__(self) -> int:
        return len(self._specs)

    def __iter__(self) -> Iterator[ProgramSpec]:
        return iter(list(self._specs))

    def specs(self, kind: Optional[str] = None) -> List[ProgramSpec]:
        with self._lock:
            out = list(self._specs)
        if kind is not None:
            out = [s for s in out if s.kind == kind]
        return out

    def match(self, label: str) -> Optional[ProgramSpec]:
        """First spec covering ``label`` (exact specs are checked
        before regex ones so a static row wins over its family
        pattern)."""
        specs = self.specs()
        for s in specs:
            if not s.regex and s.matches(label):
                return s
        for s in specs:
            if s.regex and s.matches(label):
                return s
        return None

    # -- the sweeps -----------------------------------------------------

    def coverage(
        self, observed: Optional[List[str]] = None
    ) -> Dict[str, Any]:
        """Dispatch-diet coverage: which observed program labels the
        registry predicted. ``observed`` defaults to every live
        ``ShardedFunction`` label (``compile_stats()``); pass the
        device ledger's program labels for a device-time view."""
        if observed is None:
            from ray_tpu.sharding.compile import compile_stats

            observed = [
                s["label"]
                for s in compile_stats()["per_function"]
            ]
        matched: Dict[str, str] = {}
        unmatched: List[str] = []
        for label in observed:
            spec = self.match(label)
            if spec is None:
                unmatched.append(label)
            else:
                matched[label] = spec.kind
        return {
            "specs": len(self),
            "observed": len(observed),
            "matched": matched,
            "unmatched": unmatched,
        }

    def sweep(
        self, *, kind: Optional[str] = None, warm: bool = True
    ) -> Dict[str, Any]:
        """Walk the specs (optionally one ``kind``), running each
        ``warm`` callable — the one-pass AOT pre-seed / bucket warmup.
        Errors are collected, not raised: a spec whose program can't
        build yet (batch size unknown until the first train call) must
        not abort the specs after it."""
        warmed, skipped, errors = 0, 0, []
        for spec in self.specs(kind):
            if not warm or spec.warm is None:
                skipped += 1
                continue
            try:
                spec.warm()
                warmed += 1
            except Exception as e:  # pragma: no cover - defensive
                errors.append({"label": spec.label, "error": repr(e)})
        return {
            "specs": len(self.specs(kind)),
            "warmed": warmed,
            "skipped": skipped,
            "errors": errors,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The ledger-backed view: every spec row joined against the
        device ledger's per-program device time (empty columns when
        the ledger is off) and the compile-cache stats."""
        from ray_tpu.sharding.compile import compile_stats
        from ray_tpu.telemetry import device as device_ledger

        per_fn = {
            s["label"]: s
            for s in compile_stats()["per_function"]
        }
        ledger_rows: Dict[str, Dict[str, Any]] = {}
        if device_ledger.enabled():
            for row in device_ledger.snapshot().get("programs", []):
                ledger_rows[row.get("label", "")] = row
        rows = []
        for spec in self.specs():
            row = spec.describe()
            observed = [
                lbl for lbl in per_fn if spec.matches(lbl)
            ]
            row["observed"] = observed
            row["calls"] = sum(
                per_fn[lbl]["calls"] for lbl in observed
            )
            row["traces"] = sum(
                per_fn[lbl]["traces"] for lbl in observed
            )
            dev = [
                ledger_rows[lbl]
                for lbl in ledger_rows
                if spec.matches(lbl)
            ]
            if dev:
                row["device_time_s"] = sum(
                    d.get("device_time_s", 0.0) for d in dev
                )
                row["executions"] = sum(
                    d.get("executions", 0) for d in dev
                )
            rows.append(row)
        return {"specs": rows}


# -- predictive enumeration ------------------------------------------------

_NUM = r"\d+"


def _cls(policy) -> str:
    return re.escape(type(policy).__name__)


def for_policy(
    policy, policy_id: str = "default_policy", config=None
) -> List[ProgramSpec]:
    """The executables ONE policy's config lowers. Batch sizes are
    data-dependent (resolved on first dispatch), so the learn-side
    rows are anchored regexes over the class-name label families the
    policy builds (``jax_policy._build_*``)."""
    config = config if config is not None else getattr(
        policy, "config", {}
    )
    cls = _cls(policy)
    specs: List[ProgramSpec] = [
        # the per-update learn nest (multi_learn: SAC's fused actor/
        # critic pair; learn[QMIX] has no batch suffix)
        ProgramSpec(
            rf"(?:multi_)?learn\[{cls}(?::{_NUM}(?:x{_NUM})?)?\]",
            kind="learn",
            policy_id=policy_id,
            regex=True,
        ),
        # split-phase gradient API (compute_gradients/apply_gradients)
        ProgramSpec(
            rf"grads\[{cls}\]",
            kind="grads",
            policy_id=policy_id,
            regex=True,
        ),
        ProgramSpec(
            rf"apply_grads\[{cls}\]",
            kind="grads",
            policy_id=policy_id,
            regex=True,
        ),
    ]
    if config.get("superstep", "auto") != 0:
        specs += [
            ProgramSpec(
                rf"superstep\[{cls}:{_NUM}x{_NUM}\]",
                kind="superstep",
                policy_id=policy_id,
                regex=True,
            ),
            # host-side minibatch re-stack feeding the scan
            ProgramSpec(
                rf"superstep_stack\[{_NUM}\]",
                kind="stack",
                policy_id=policy_id,
                regex=True,
            ),
        ]
    if config.get("jax_fused_rollout", True) or (
        config.get("env_backend") == "jax"
    ):
        specs += [
            ProgramSpec(
                rf"rollout_superstep\[{cls}:{_NUM}x{_NUM}\]",
                kind="rollout",
                policy_id=policy_id,
                regex=True,
            ),
            ProgramSpec(
                rf"jax_rollout\[\w+:{_NUM}x{_NUM}\]",
                kind="rollout",
                policy_id=policy_id,
                regex=True,
            ),
        ]
    return specs


def _replay_specs(policy_id: str, prioritized: bool) -> List[ProgramSpec]:
    pid = re.escape(policy_id)
    specs = [
        ProgramSpec(
            rf"replay_insert\[{pid}\]",
            kind="replay",
            policy_id=policy_id,
            regex=True,
        ),
        ProgramSpec(
            rf"replay_sample\[{pid}\]",
            kind="replay",
            policy_id=policy_id,
            regex=True,
        ),
    ]
    if prioritized:
        specs += [
            ProgramSpec(
                rf"replay_draw_sample\[{pid}:{_NUM}\]",
                kind="replay",
                policy_id=policy_id,
                regex=True,
            ),
            ProgramSpec(
                rf"tree_draw_sets\[{pid}:{_NUM}x{_NUM}\]",
                kind="tree",
                policy_id=policy_id,
                regex=True,
            ),
            ProgramSpec(
                rf"tree_update\[{pid}:{_NUM}x{_NUM}\]",
                kind="tree",
                policy_id=policy_id,
                regex=True,
            ),
            ProgramSpec(
                rf"tree_draw\[{pid}:{_NUM}(?:x{_NUM})*\]",
                kind="tree",
                policy_id=policy_id,
                regex=True,
            ),
        ]
    return specs


def _uses_replay(config) -> bool:
    # replay-driven algorithms all size a ring through one of these
    return bool(
        config.get("buffer_size")
        or config.get("replay_buffer_size")
        or (config.get("replay_buffer_config") or {}).get("capacity")
    )


def for_algorithm(algo) -> ProgramRegistry:
    """Enumerate every program the algorithm's current config lowers:
    one spec family per (policy × subsystem). Serve buckets attach
    later — ``BatchedPolicyServer`` registers its own exact rows when
    it is constructed against this algorithm."""
    reg = ProgramRegistry()
    config = getattr(algo, "config", {}) or {}
    try:
        lw = algo.workers.local_worker()
        policy_map = getattr(lw, "policy_map", None) or {}
    except Exception:  # pragma: no cover - partially built algos
        policy_map = {}
    replay = _uses_replay(config)
    prioritized = bool(
        config.get("prioritized_replay")
        or (config.get("replay_buffer_config") or {}).get(
            "prioritized_replay"
        )
    )
    for pid, pol in policy_map.items():
        reg.extend(
            for_policy(pol, policy_id=pid, config=config)
        )
        if replay:
            reg.extend(_replay_specs(pid, prioritized))
        if replay and pid != "default_policy":
            # shared single-buffer algorithms keep the default label
            reg.extend(
                _replay_specs("default_policy", prioritized)
            )
    # APEX shards its ring: one insert/sample family per shard label
    if replay and "apex" in type(algo).__name__.lower():
        reg.add_program(
            r"replay_(?:insert|sample|draw_sample)\[apex_shard_\d+(?::\d+)?\]",
            kind="replay",
            regex=True,
        )
        reg.add_program(
            r"tree_(?:update|draw|draw_sets)\[apex_shard_\d+(?::\d+(?:x\d+)*)?\]",
            kind="tree",
            regex=True,
        )
    # QMIX's episode stacker rides its own label
    reg.add_program(
        r"qmix_episodes", kind="stack", regex=False
    ) if "qmix" in type(algo).__name__.lower() else None
    return reg
