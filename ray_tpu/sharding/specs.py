"""NamedSharding / PartitionSpec builders.

The placement vocabulary of the learner plane, as first-class
functions instead of per-call-site constructions:

  - params / optimizer state / aux (target nets, frame pools):
    replicated — every shard holds the full tree;
  - SampleBatch columns: sharded over the leading (row) dim on the
    mesh's data axis;
  - ragged leading dims (a column whose row count doesn't divide the
    shard count) fall back to replication rather than erroring — the
    ``get_naive_sharding`` pattern from the retrieved references.

Everything derives the axis name from the mesh object, so specs work
on both the ``("batch",)`` meshes this package builds and the legacy
``("data",)`` meshes of ``ray_tpu.parallel``.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.sharding.mesh import data_axis, num_shards


def replicated(mesh: Mesh) -> NamedSharding:
    """Full copy on every device (params, opt state, scalars)."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, ndim_prefix: int = 1) -> NamedSharding:
    """Leading-dim row sharding over the data axis. ``ndim_prefix``
    places the axis deeper, e.g. 2 -> P(None, axis) for (T, B, ...)
    layouts."""
    spec = (None,) * (ndim_prefix - 1) + (data_axis(mesh),)
    return NamedSharding(mesh, P(*spec))


def leaf_sharding(x, mesh: Mesh) -> NamedSharding:
    """Per-array placement: shard rows when the leading dim divides
    the shard count, otherwise replicate (uneven-dim fallback)."""
    shape = getattr(x, "shape", ())
    if len(shape) >= 1 and shape[0] % num_shards(mesh) == 0 and shape[0] > 0:
        return batch_sharded(mesh)
    return replicated(mesh)


def sharding_tree(tree, mesh: Mesh, replicate_keys: Iterable[str] = ()):
    """Per-leaf sharding tree for a (possibly nested) batch tree.
    Top-level dict keys in ``replicate_keys`` pin to replication no
    matter their shape — e.g. the deduplicated frame pool, which every
    shard gathers from locally."""
    replicate_keys = set(replicate_keys)
    if isinstance(tree, dict) and replicate_keys:
        return {
            k: (
                jax.tree_util.tree_map(
                    lambda x: replicated(mesh), v
                )
                if k in replicate_keys
                else jax.tree_util.tree_map(
                    lambda x: leaf_sharding(x, mesh), v
                )
            )
            for k, v in tree.items()
        }
    return jax.tree_util.tree_map(lambda x: leaf_sharding(x, mesh), tree)


def tree_nbytes(tree) -> int:
    """Total array bytes of a (host or device) pytree — the H2D
    payload accounting unit behind ``ray_tpu_h2d_bytes_total``
    (telemetry/metrics.py): callers count a tree right before its
    ``device_put`` so the counter reflects what actually crosses the
    wire."""
    return int(
        sum(
            int(getattr(x, "nbytes", 0))
            for x in jax.tree_util.tree_leaves(tree)
        )
    )


def shard_batch(
    tree,
    mesh: Mesh,
    replicate_keys: Iterable[str] = (),
    *,
    block: bool = False,
):
    """``jax.device_put`` a host tree onto the mesh with per-leaf
    shardings. ``block=True`` waits for the transfer (honest timing;
    otherwise dispatch is async and overlaps the caller)."""
    dev = jax.device_put(
        tree, sharding_tree(tree, mesh, replicate_keys)
    )
    if block:
        jax.block_until_ready(dev)
    return dev
