"""NamedSharding / PartitionSpec builders.

The placement vocabulary of the learner plane, as first-class
functions instead of per-call-site constructions:

  - params / optimizer state / aux (target nets, frame pools):
    replicated by default — every shard holds the full tree — or
    **per-leaf partitioned** over the mesh's ``"model"`` axis via
    ordered name-pattern rules (:func:`param_pspecs`, megatron-style
    defaults in :func:`default_partition_rules`);
  - SampleBatch columns: sharded over the leading (row) dim on the
    mesh's data axis;
  - ragged leading dims (a column whose row count doesn't divide the
    shard count) fall back to replication rather than erroring — the
    ``get_naive_sharding`` pattern from the retrieved references. The
    fallback is **observable**: it fires a
    ``jit:fallback_replicated`` trace event and bumps
    ``ray_tpu_sharding_fallback_replicated_total`` so a mis-sharded
    hot path shows in the Prometheus scrape instead of just running
    slow.

Everything derives the axis name from the mesh object, so specs work
on both the ``("batch",)`` meshes this package builds and the legacy
``("data",)`` meshes of ``ray_tpu.parallel``.
"""

from __future__ import annotations

import collections
import functools
import re
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.sharding.mesh import MODEL_AXIS, data_axis, num_shards

# -- dispatch-diet caches (benchmarks/MFU.md "dispatch overhead") ------
#
# NamedSharding construction is pure but not free, and the hot call
# sites (per-batch ``sharding_tree`` in JaxPolicy.batch_shardings, the
# per-call replicated()/batch_sharded() in feeders and supersteps)
# used to rebuild identical objects every dispatch. Both builders
# memoize on the (hashable) mesh; ``sharding_tree`` additionally keeps
# a bounded signature-keyed memo of resolved trees with an
# object-identity fast path for the immediately-previous tree. A
# genuinely changed sharding (new mesh, a column whose leading dim
# stops dividing the shard count, a changed replicate set) changes the
# signature and misses to the full derivation — the invalidation
# contract tests/test_dispatch_diet.py pins.


@functools.lru_cache(maxsize=128)
def replicated(mesh: Mesh) -> NamedSharding:
    """Full copy on every device (params, opt state, scalars)."""
    return NamedSharding(mesh, P())


@functools.lru_cache(maxsize=128)
def batch_sharded(mesh: Mesh, ndim_prefix: int = 1) -> NamedSharding:
    """Leading-dim row sharding over the data axis. ``ndim_prefix``
    places the axis deeper, e.g. 2 -> P(None, axis) for (T, B, ...)
    layouts."""
    spec = (None,) * (ndim_prefix - 1) + (data_axis(mesh),)
    return NamedSharding(mesh, P(*spec))


def _note_fallback_replicated(shape) -> None:
    """A batch leaf that SHOULD row-shard fell back to replication
    (ragged leading dim on a multi-shard mesh): emit the
    ``jit:fallback_replicated`` event + counter so the degraded
    placement is visible in the scrape, not just slow."""
    try:
        from ray_tpu.telemetry import metrics as _tm

        _tm.inc_sharding_fallback()
        from ray_tpu.util import tracing as _tr

        if _tr.is_enabled():
            _tr.event(
                "jit:fallback_replicated", shape=str(tuple(shape))
            )
    except Exception:  # telemetry must never break placement
        pass


def leaf_sharding(x, mesh: Mesh) -> NamedSharding:
    """Per-array placement: shard rows when the leading dim divides
    the shard count, otherwise replicate (uneven-dim fallback —
    counted, see :func:`_note_fallback_replicated`)."""
    shape = getattr(x, "shape", ())
    if len(shape) >= 1 and shape[0] % num_shards(mesh) == 0 and shape[0] > 0:
        return batch_sharded(mesh)
    if len(shape) >= 1 and shape[0] > 0 and num_shards(mesh) > 1:
        _note_fallback_replicated(shape)
    return replicated(mesh)


# lazily-bound telemetry.fleetview module; the cross-process put_global
# path stamps a collective drain-point arrival there so the fleet
# aggregator can attribute which host reached the placement last
# (record_arrival is one flag check when no exporter runs)
_FLEETVIEW = None


def _note_collective_arrival(point: str) -> None:
    global _FLEETVIEW
    if _FLEETVIEW is None:
        try:
            from ray_tpu.telemetry import fleetview

            _FLEETVIEW = fleetview
        except Exception:  # telemetry must never break placement
            return
    try:
        _FLEETVIEW.record_arrival(point)
    except Exception:
        pass


@functools.lru_cache(maxsize=128)
def mesh_spans_processes(mesh: Mesh) -> bool:
    """Whether this mesh's devices live in more than one jax process —
    the DCN case, where a plain ``device_put`` of a host value cannot
    address the remote shards and placement must go through
    :func:`put_global` instead."""
    try:
        return (
            len({d.process_index for d in mesh.devices.flat}) > 1
        )
    except Exception:
        return False


def put_global(x, sharding: NamedSharding):
    """``device_put`` that also works when the sharding's mesh spans
    processes (multi-host learner fleets, docs/fleet.md).

    Single-process meshes take the plain ``jax.device_put`` path —
    byte-identical behavior to before. On a cross-process mesh, every
    process must call this with the SAME host value (the lockstep SPMD
    contract the multi-host tests pin): each process carves out the
    row block its addressable shards own and the global array is
    assembled via ``jax.make_array_from_process_local_data`` — the
    device-replay rings allocate their cross-host shards through
    exactly this path."""
    mesh = getattr(sharding, "mesh", None)
    if mesh is None or not mesh_spans_processes(mesh):
        return jax.device_put(x, sharding)
    # collective drain point: every process reaches this placement in
    # lockstep, so the arrival stamp lets the fleet aggregator name
    # the straggler (telemetry/fleetview.py)
    _note_collective_arrival("put_global")
    import numpy as np

    arr = np.asarray(x)
    # the union of this process's shard index-boxes (contiguous per
    # dim for the 1-D row layouts the learner uses)
    idx_map = sharding.addressable_devices_indices_map(arr.shape)
    local = arr
    if idx_map:
        slices = []
        for d in range(arr.ndim):
            starts = [
                (idx[d].start or 0) for idx in idx_map.values()
            ]
            stops = [
                (
                    idx[d].stop
                    if idx[d].stop is not None
                    else arr.shape[d]
                )
                for idx in idx_map.values()
            ]
            slices.append(slice(min(starts), max(stops)))
        local = arr[tuple(slices)]
    return jax.make_array_from_process_local_data(
        sharding, local, arr.shape
    )


# signature -> (resolved tree, fallback shapes) LRU; one entry per
# distinct (mesh, column-name, placement-kind, replicate-set) batch
# signature — steady training resolves its per-batch tree with dict
# lookups instead of per-leaf reconstruction
_TREE_MEMO: "collections.OrderedDict" = collections.OrderedDict()
_TREE_MEMO_MAX = 256
_TREE_MEMO_LOCK = threading.Lock()
# object-identity fast path: (id(tree), signature-independent reuse is
# NOT safe — ids recycle), so the identity memo pins the tree object
# itself alongside its resolved result
_LAST_TREE: Optional[Tuple[object, Mesh, frozenset, dict, tuple]] = None


def clear_sharding_caches() -> None:
    """Drop the resolved-tree memos (tests; mesh teardown)."""
    global _LAST_TREE
    with _TREE_MEMO_LOCK:
        _TREE_MEMO.clear()
        _LAST_TREE = None
    replicated.cache_clear()
    batch_sharded.cache_clear()


def _flat_signature(tree: dict, mesh: Mesh, replicate_keys) -> Optional[tuple]:
    """Placement signature of a flat dict-of-arrays batch: per column,
    which of the three leaf_sharding outcomes applies (replicate /
    row-shard / ragged-fallback-replicate). None when the tree isn't
    the flat prepared-batch shape — the caller takes the full path."""
    n = num_shards(mesh)
    sig = []
    for k, v in tree.items():
        shape = getattr(v, "shape", None)
        if shape is None or isinstance(v, dict):
            return None
        if k in replicate_keys:
            kind = 0
        elif len(shape) >= 1 and shape[0] > 0 and shape[0] % n == 0:
            kind = 1
        elif len(shape) >= 1 and shape[0] > 0 and n > 1:
            kind = 2  # ragged: replicate + counted fallback
        else:
            kind = 0
        sig.append((k, kind) if kind != 2 else (k, 2, tuple(shape)))
    return tuple(sig)


def sharding_tree(tree, mesh: Mesh, replicate_keys: Iterable[str] = ()):
    """Per-leaf sharding tree for a (possibly nested) batch tree.
    Top-level dict keys in ``replicate_keys`` pin to replication no
    matter their shape — e.g. the deduplicated frame pool, which every
    shard gathers from locally.

    Flat dict-of-arrays trees (every prepared train batch) resolve
    through a signature-keyed memo: the NamedSharding tree is built
    once per distinct placement signature and reused, with the ragged
    fallback still counted per call (the degraded placement stays
    visible in the scrape). Nested trees take the full per-leaf
    derivation every time."""
    global _LAST_TREE
    replicate_keys = frozenset(replicate_keys)
    if type(tree) is dict:
        # identity fast path: the same tree object re-resolved against
        # the same mesh (feeders re-deriving placement for a batch they
        # already resolved) costs three `is` checks
        last = _LAST_TREE
        if (
            last is not None
            and last[0] is tree
            and last[1] is mesh
            and last[2] == replicate_keys
        ):
            for shape in last[4]:
                _note_fallback_replicated(shape)
            return dict(last[3])
        sig = _flat_signature(tree, mesh, replicate_keys)
        if sig is not None:
            key = (mesh, sig, replicate_keys)
            with _TREE_MEMO_LOCK:
                hit = _TREE_MEMO.get(key)
                if hit is not None:
                    _TREE_MEMO.move_to_end(key)
            if hit is None:
                out = {}
                fallbacks = []
                for entry in sig:
                    k, kind = entry[0], entry[1]
                    out[k] = (
                        batch_sharded(mesh)
                        if kind == 1
                        else replicated(mesh)
                    )
                    if kind == 2:
                        fallbacks.append(entry[2])
                hit = (out, tuple(fallbacks))
                with _TREE_MEMO_LOCK:
                    _TREE_MEMO[key] = hit
                    while len(_TREE_MEMO) > _TREE_MEMO_MAX:
                        _TREE_MEMO.popitem(last=False)
            for shape in hit[1]:
                _note_fallback_replicated(shape)
            _LAST_TREE = (tree, mesh, replicate_keys, hit[0], hit[1])
            return dict(hit[0])
    if isinstance(tree, dict) and replicate_keys:
        return {
            k: (
                jax.tree_util.tree_map(
                    lambda x: replicated(mesh), v
                )
                if k in replicate_keys
                else jax.tree_util.tree_map(
                    lambda x: leaf_sharding(x, mesh), v
                )
            )
            for k, v in tree.items()
        }
    return jax.tree_util.tree_map(lambda x: leaf_sharding(x, mesh), tree)


def tree_nbytes(tree) -> int:
    """Total array bytes of a (host or device) pytree — the H2D
    payload accounting unit behind ``ray_tpu_h2d_bytes_total``
    (telemetry/metrics.py): callers count a tree right before its
    ``device_put`` so the counter reflects what actually crosses the
    wire."""
    return int(
        sum(
            int(getattr(x, "nbytes", 0))
            for x in jax.tree_util.tree_leaves(tree)
        )
    )


# -- per-leaf partitioned param trees (2-D data x model meshes) --------
#
# The rule grammar (docs/sharding.md "2-D mesh & param partitioning"):
# an ordered sequence of ``(pattern, spec)`` pairs. ``pattern`` is a
# regex searched against the leaf's "/"-joined key path (e.g.
# "layer_0/attn/wq"); the FIRST match wins. ``spec`` is a
# PartitionSpec (or a plain tuple of axis names / None) naming, per
# array dimension, the mesh axis that splits it. Axes absent from the
# mesh prune to None, so rules written against "model" degrade to
# replication on a 1-D data mesh. Anything unmatched replicates.


def default_partition_rules() -> Tuple:
    """Megatron-style defaults for the transformer torso
    (``models/transformer.py`` naming): attention QKV projections
    split on the head dim, the output projection on its input (head)
    dim, MLP up on its output dim, MLP down on its input dim —
    embeddings, layernorms, heads and biases-of-reduced-outputs
    replicated. Ordered; first match wins; ``.*`` -> replicate is the
    implicit tail."""
    return (
        (r"attn/w[qkv]$", P(None, MODEL_AXIS, None)),
        (r"attn/b[qkv]$", P(MODEL_AXIS)),
        (r"attn/wo$", P(MODEL_AXIS, None, None)),
        (r"mlp/w_up$", P(None, MODEL_AXIS)),
        (r"mlp/b_up$", P(MODEL_AXIS)),
        (r"mlp/w_down$", P(MODEL_AXIS, None)),
    )


def _is_pspec(x) -> bool:
    return isinstance(x, P)


def _path_names(path) -> Tuple[str, ...]:
    out = []
    for k in path:
        if hasattr(k, "key"):
            out.append(str(k.key))
        elif hasattr(k, "name"):
            out.append(str(k.name))
        elif hasattr(k, "idx"):
            out.append(str(k.idx))
        else:  # pragma: no cover - future key kinds
            out.append(str(k))
    return tuple(out)


def _fit_spec(spec, ndim: int, mesh: Mesh):
    """Normalize one rule spec against a concrete leaf: tuple -> P,
    axes the mesh doesn't have -> None, rank mismatches that would
    drop a named axis -> replicate (never silently mis-place)."""
    entries = list(spec) if not isinstance(spec, P) else list(spec)
    entries = [
        (e if e is None or e in mesh.axis_names else None)
        for e in entries
    ]
    if len(entries) > ndim:
        if any(e is not None for e in entries[ndim:]):
            return P()
        entries = entries[:ndim]
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def param_pspecs(tree, mesh: Mesh, rules: Sequence) -> object:
    """Per-leaf :class:`PartitionSpec` tree for a param tree, from
    ordered ``(pattern, spec)`` name rules (first match wins; no
    match -> replicated). Leaf names are the "/"-joined key paths of
    the tree."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def one(path, x):
        name = "/".join(_path_names(path))
        ndim = len(getattr(x, "shape", ()))
        for pat, spec in compiled:
            if pat.search(name):
                return _fit_spec(spec, ndim, mesh)
        return P()

    return jax.tree_util.tree_map_with_path(one, tree)


def named_tree(mesh: Mesh, pspec_tree):
    """PartitionSpec tree -> NamedSharding tree (same structure) for
    ``sharded_jit`` in/out specs. A bare ``P()`` maps to
    :func:`replicated`."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspec_tree, is_leaf=_is_pspec
    )


def param_sharding(tree, mesh: Mesh, rules: Sequence):
    """Per-leaf :class:`NamedSharding` tree for a param tree — the
    builder the learn/serve/rollout call sites hand to
    ``jax.device_put`` and ``sharded_jit`` (tentpole surface of
    docs/sharding.md)."""
    return named_tree(mesh, param_pspecs(tree, mesh, rules))


def state_pspecs(state, params, params_pspecs) -> object:
    """Spec tree for a params-derived state tree (optimizer moments,
    target networks): each state leaf inherits the spec of the param
    whose key path is a suffix of the leaf's path with the same shape
    (longest suffix wins); everything else — step counts, scalars —
    replicates. This is how per-leaf placement flows through
    ``optax`` states and aux target trees without those containers
    knowing about rules."""
    pairs = []
    pflat, _ = jax.tree_util.tree_flatten_with_path(params)
    specs_flat = jax.tree_util.tree_leaves(
        params_pspecs, is_leaf=_is_pspec
    )
    for (path, leaf), spec in zip(pflat, specs_flat):
        pairs.append(
            (_path_names(path), tuple(getattr(leaf, "shape", ())), spec)
        )

    def one(path, x):
        names = _path_names(path)
        shape = tuple(getattr(x, "shape", ()))
        best = None
        for pnames, pshape, spec in pairs:
            if (
                len(pnames) <= len(names)
                and names[len(names) - len(pnames):] == pnames
                and pshape == shape
            ):
                if best is None or len(pnames) > best[0]:
                    best = (len(pnames), spec)
        return best[1] if best is not None else P()

    return jax.tree_util.tree_map_with_path(one, state)


def tree_shard_nbytes(tree, pspec_tree, mesh: Mesh) -> int:
    """Per-device bytes of a partitioned tree: each leaf's bytes
    divided by the product of the mesh-axis sizes its spec names
    (replicated leaves count full size on every shard) — the number
    behind ``ray_tpu_params_bytes{placement="per_shard"}``."""
    leaves = jax.tree_util.tree_leaves(tree)
    specs = jax.tree_util.tree_leaves(pspec_tree, is_leaf=_is_pspec)
    total = 0
    for x, spec in zip(leaves, specs):
        denom = 1
        for entry in spec:
            for ax in (
                entry if isinstance(entry, (tuple, list)) else (entry,)
            ):
                if ax is not None:
                    denom *= int(mesh.shape[ax])
        total += int(getattr(x, "nbytes", 0)) // max(1, denom)
    return int(total)


def shard_batch(
    tree,
    mesh: Mesh,
    replicate_keys: Iterable[str] = (),
    *,
    block: bool = False,
):
    """``jax.device_put`` a host tree onto the mesh with per-leaf
    shardings. ``block=True`` waits for the transfer (honest timing;
    otherwise dispatch is async and overlaps the caller)."""
    dev = jax.device_put(
        tree, sharding_tree(tree, mesh, replicate_keys)
    )
    if block:
        jax.block_until_ready(dev)
    return dev
