"""Ahead-of-time compiled-program cache: cold starts that skip XLA.

A fresh serve replica (or an elastic joiner's learn program) pays the
full warmup compile storm before its first useful dispatch — one XLA
compile per bucket shape for a policy server, seconds each at real
geometry. The programs are identical across the fleet: same policy,
same mesh topology, same bucket contract. This module makes that
redundancy a cache hit.

The mechanism is the ``Lowered``/pjit-AOT machinery (SNIPPETS [1],
``jax.experimental.serialize_executable``): a ``sharded_jit`` program
is lowered and compiled ahead of time, the **compiled XLA executable**
is serialized (not StableHLO — deserialization skips XLA entirely,
measured ~20x faster than a live compile even for toy programs), and
the payload lands in a persistent on-disk cache shared across the
fleet. ``ShardedFunction.aot_warmup`` restores it; on a hit the
executable is installed as the function's dispatch path with ZERO
fresh compiles, ledger-registered with ``compile_s=0`` and
``source="aot_cache"`` so MFU/compile accounting stays honest.

Keying and the fallback contract (docs/serving.md "the front door"):

- entries are keyed by a **fingerprint** (jax/jaxlib version, backend
  platform, device kind, device count — serialized executables are
  only valid on the topology+toolchain that built them), the program
  label, and the abstract input signature — which carries the mesh
  geometry of the program's shardings (``compile.py``
  ``_mesh_geometry_token``), so one process can hold entries for
  SEVERAL mesh geometries at once (the fleet pre-seeds its ±1-host
  resize geometries ahead of a preemption, PR 17);
- ANY mismatch — different version, different topology, a torn or
  corrupt file, an API that refuses to deserialize — is a plain cache
  miss: the caller compiles live (and repopulates the cache), never
  errors. A stale executable that slips through keying and fails at
  dispatch falls back the same way (``ShardedFunction.__call__``);
- writes go through a background cache-writer thread with the PR-2
  atomic-write discipline (temp + fsync + ``os.replace``), so a
  replica killed mid-write never leaves a torn entry for the fleet.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import queue
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.util import tracing

# bump when the entry layout changes: old entries become misses
# (2: mesh-geometry token joined the signature — pre-format entries
# would collide across geometries, so they must miss)
FORMAT = 2


def supported() -> bool:
    """Whether this jax build can serialize compiled executables."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401

        return True
    except Exception:
        return False


def fingerprint() -> Dict[str, Any]:
    """The validity domain of a serialized executable: the toolchain
    that compiled it and the device topology it was compiled for. Any
    component moving invalidates every entry (by key)."""
    import jax
    import jaxlib

    try:
        devices = jax.devices()
        kind = devices[0].device_kind
        platform = devices[0].platform
        n = len(devices)
    except Exception:
        kind, platform, n = "unknown", "unknown", 0
    return {
        "format": FORMAT,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": platform,
        "device_kind": kind,
        "n_devices": n,
    }


def entry_key(label: str, signature: Any, fp: Dict[str, Any]) -> str:
    """Stable digest naming one cache entry: fingerprint + program
    label + abstract input signature (the same signature unit the
    device ledger's recompile forensics diff)."""
    h = hashlib.sha256()
    h.update(repr(sorted(fp.items())).encode())
    h.update(b"\x00")
    h.update(label.encode())
    h.update(b"\x00")
    h.update(repr(signature).encode())
    return h.hexdigest()


class AOTCompileCache:
    """Persistent on-disk cache of serialized compiled executables,
    shared across the fleet (point every replica at the same
    directory — NFS/GCS-fuse at fleet scale, tmpdir in tests).

    ``load`` returns a ready-to-dispatch executable or None (every
    failure mode is a miss); ``save`` serializes on the cache-writer
    thread so warmup never blocks on pickling + fsync.
    """

    def __init__(self, root: str, *, writer: bool = True):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._fp = fingerprint()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.saves = 0
        self.load_errors = 0
        self.save_errors = 0
        self._writer_q: "queue.Queue[Optional[Tuple]]" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        if writer:
            self._writer = threading.Thread(
                target=self._writer_run, daemon=True,
                name="aot_cache_writer",
            )
            self._writer.start()

    # -- keying ----------------------------------------------------------

    @property
    def fingerprint_dict(self) -> Dict[str, Any]:
        return dict(self._fp)

    def path_for(self, label: str, signature: Any) -> str:
        return os.path.join(
            self.root, entry_key(label, signature, self._fp) + ".aot"
        )

    # -- load (any failure is a miss) ------------------------------------

    def load(self, label: str, signature: Any):
        """Deserialize the cached executable for (label, signature) on
        the CURRENT fingerprint, or None. Version/topology mismatches
        never reach this far (they key to different paths); torn or
        corrupt files and deserialization refusals count as
        ``load_errors`` and fall through to a miss."""
        path = self.path_for(label, signature)
        if not os.path.exists(path):
            self._count("misses")
            return None
        try:
            with open(path, "rb") as f:
                entry = pickle.load(f)
            # defense in depth beyond the keyed filename: a hand-moved
            # or hash-colliding entry still must match exactly
            if entry.get("fingerprint") != self._fp:
                raise ValueError("fingerprint mismatch")
            if entry.get("label") != label:
                raise ValueError("label mismatch")
            from jax.experimental import serialize_executable as se

            loaded = se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"]
            )
        except Exception:
            self._count("load_errors")
            self._count("misses")
            _metric("load_error")
            return None
        self._count("hits")
        _metric("hit")
        tracing.event("aot:restore", label=label, path=path)
        return loaded

    # -- save (cache-writer thread) --------------------------------------

    def save(self, label: str, signature: Any, compiled) -> None:
        """Queue one compiled executable for serialization + atomic
        write. Returns immediately; ``flush()`` joins the queue (bench
        and tests; a serving replica never needs to)."""
        self._writer_q.put((label, signature, compiled))
        if self._writer is None:
            self._drain_one()

    def flush(self, timeout_s: float = 30.0) -> None:
        """Block until every queued save hit the disk (unfinished
        TASKS, not just an empty queue — the writer may be mid-write
        on the last entry)."""
        deadline = time.monotonic() + timeout_s
        while (
            self._writer_q.unfinished_tasks > 0
            and time.monotonic() < deadline
        ):
            if self._writer is None:
                self._drain_one()
            else:
                time.sleep(0.01)

    # ray-tpu: thread=aot-writer
    def _writer_run(self) -> None:
        while True:
            item = self._writer_q.get()
            try:
                if item is None:
                    return
                self._write_entry(*item)
            finally:
                self._writer_q.task_done()

    def _drain_one(self) -> None:
        try:
            item = self._writer_q.get_nowait()
        except queue.Empty:
            return
        try:
            if item is not None:
                self._write_entry(*item)
        finally:
            self._writer_q.task_done()

    def _write_entry(self, label, signature, compiled) -> None:
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps(
                {
                    "fingerprint": self._fp,
                    "label": label,
                    "signature": repr(signature),
                    "created": time.time(),
                    "payload": payload,
                    "in_tree": in_tree,
                    "out_tree": out_tree,
                }
            )
            from ray_tpu.util.atomic_io import atomic_write

            path = self.path_for(label, signature)
            atomic_write(path, lambda f: f.write(blob))
        except Exception:
            self._count("save_errors")
            _metric("save_error")
            return
        self._count("saves")
        _metric("save")

    # -- introspection ---------------------------------------------------

    def _count(self, field: str) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + 1)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "root": self.root,
                "hits": self.hits,
                "misses": self.misses,
                "saves": self.saves,
                "load_errors": self.load_errors,
                "save_errors": self.save_errors,
                "entries": sum(
                    1
                    for n in os.listdir(self.root)
                    if n.endswith(".aot")
                )
                if os.path.isdir(self.root)
                else 0,
            }

    def stop(self, join_timeout: float = 10.0) -> None:
        if self._writer is not None and self._writer.is_alive():
            self._writer_q.put(None)
            self._writer.join(timeout=join_timeout)
            self._writer = None


def _metric(event: str) -> None:
    try:
        from ray_tpu.telemetry import metrics as tm

        tm.inc_aot_cache_event(event)
    except Exception:
        pass


def resolve_cache(cache) -> Optional[AOTCompileCache]:
    """Accept an :class:`AOTCompileCache`, a directory path, or None
    (also reading ``RAY_TPU_AOT_CACHE`` as the no-config activation
    path, mirroring the device ledger's env knob)."""
    if cache is None:
        env = os.environ.get("RAY_TPU_AOT_CACHE")
        if not env:
            return None
        cache = env
    if isinstance(cache, AOTCompileCache):
        return cache
    return AOTCompileCache(str(cache))
