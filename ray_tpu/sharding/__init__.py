"""ray_tpu.sharding — the mesh-based sharding runtime of the learner.

Replaces the per-call pmap/shard-map shims with a first-class layer
(docs/sharding.md):

  - :mod:`~ray_tpu.sharding.mesh`    mesh construction (cached, CPU
    fallback, simulated devices), ``("batch",)`` data mesh today with
    the ``"model"`` axis name reserved;
  - :mod:`~ray_tpu.sharding.specs`   NamedSharding builders: replicated
    param trees, row-sharded batch columns, per-leaf trees with the
    ragged-leading-dim fallback;
  - :mod:`~ray_tpu.sharding.compile` ``sharded_jit`` — jit with
    shardings + donation + compile-cache stats.

Policies select the backend via ``config["sharding_backend"]``:
``"mesh"`` (default) lowers the learn program through ``sharded_jit``
with explicit shardings on a ``("batch",)`` mesh; ``"pmap"`` keeps the
legacy ``ray_tpu.parallel`` path (a ``("data",)`` mesh, placement left
to device_put) — fixed-seed results are bit-identical between the two
on one device.
"""

from ray_tpu.sharding.compile import (
    ShardedFunction,
    compile_stats,
    dispatch_diet_enabled,
    f64_scope,
    set_dispatch_diet,
    sharded_jit,
)
from ray_tpu.sharding.mesh import (
    BATCH_AXIS,
    MODEL_AXIS,
    available_devices,
    clear_mesh_cache,
    data_axis,
    get_mesh,
    global_devices,
    model_axis,
    model_shards,
    num_shards,
    resolve_hosts,
    resolve_model_parallel,
    simulated_device_env,
)
from ray_tpu.sharding.specs import (
    batch_sharded,
    clear_sharding_caches,
    default_partition_rules,
    leaf_sharding,
    mesh_spans_processes,
    named_tree,
    param_pspecs,
    param_sharding,
    put_global,
    replicated,
    shard_batch,
    sharding_tree,
    state_pspecs,
    tree_nbytes,
    tree_shard_nbytes,
)
from ray_tpu.sharding.registry import (
    ProgramRegistry,
    ProgramSpec,
    for_algorithm as registry_for_algorithm,
)
from ray_tpu.sharding.superstep import (
    build_stack_fn,
    build_superstep_fn,
    resolve_superstep,
)


def resolve_mesh(config):
    """The mesh a policy should learn on, per config: an injected
    ``_mesh`` (Algorithm.setup, multi-host tests) wins; otherwise the
    backend decides — ``"mesh"`` builds through this package,
    ``"pmap"`` through the legacy ``ray_tpu.parallel`` adapter (axis
    named ``"data"``), keeping that path byte-compatible.
    ``sharding(hosts=N)`` builds over the GLOBAL device view (every
    process of the jax.distributed runtime — the DCN × ICI mesh of
    docs/fleet.md) instead of this process's local devices."""
    m = config.get("_mesh")
    if m is not None:
        return m
    if config.get("sharding_backend", "mesh") == "pmap":
        from ray_tpu.parallel import mesh as _legacy

        return _legacy.make_mesh()
    hosts = resolve_hosts(config)
    mp = resolve_model_parallel(config)
    if hosts > 1:
        devs = global_devices(hosts)
        if mp:
            return get_mesh(
                devices=devs,
                axis_shapes=[
                    (BATCH_AXIS, len(devs) // mp),
                    (MODEL_AXIS, mp),
                ],
            )
        return get_mesh(devices=devs)
    if mp:
        devs = list(available_devices())
        return get_mesh(
            devices=devs,
            axis_shapes=[
                (BATCH_AXIS, len(devs) // mp),
                (MODEL_AXIS, mp),
            ],
        )
    return get_mesh()


__all__ = [
    "BATCH_AXIS",
    "MODEL_AXIS",
    "ProgramRegistry",
    "ProgramSpec",
    "ShardedFunction",
    "registry_for_algorithm",
    "available_devices",
    "batch_sharded",
    "build_stack_fn",
    "build_superstep_fn",
    "default_partition_rules",
    "resolve_superstep",
    "clear_mesh_cache",
    "compile_stats",
    "data_axis",
    "f64_scope",
    "get_mesh",
    "global_devices",
    "leaf_sharding",
    "mesh_spans_processes",
    "model_axis",
    "model_shards",
    "named_tree",
    "num_shards",
    "param_pspecs",
    "param_sharding",
    "put_global",
    "replicated",
    "resolve_hosts",
    "resolve_mesh",
    "resolve_model_parallel",
    "shard_batch",
    "sharded_jit",
    "sharding_tree",
    "simulated_device_env",
    "state_pspecs",
    "tree_nbytes",
    "tree_shard_nbytes",
]
