"""ray_tpu.sharding — the mesh-based sharding runtime of the learner.

Replaces the per-call pmap/shard-map shims with a first-class layer
(docs/sharding.md):

  - :mod:`~ray_tpu.sharding.mesh`    mesh construction (cached, CPU
    fallback, simulated devices), ``("batch",)`` data mesh today with
    the ``"model"`` axis name reserved;
  - :mod:`~ray_tpu.sharding.specs`   NamedSharding builders: replicated
    param trees, row-sharded batch columns, per-leaf trees with the
    ragged-leading-dim fallback;
  - :mod:`~ray_tpu.sharding.compile` ``sharded_jit`` — jit with
    shardings + donation + compile-cache stats.

Policies select the backend via ``config["sharding_backend"]``:
``"mesh"`` (default) lowers the learn program through ``sharded_jit``
with explicit shardings on a ``("batch",)`` mesh; ``"pmap"`` keeps the
legacy ``ray_tpu.parallel`` path (a ``("data",)`` mesh, placement left
to device_put) — fixed-seed results are bit-identical between the two
on one device.
"""

from ray_tpu.sharding.compile import (
    ShardedFunction,
    compile_stats,
    sharded_jit,
)
from ray_tpu.sharding.mesh import (
    BATCH_AXIS,
    MODEL_AXIS,
    available_devices,
    clear_mesh_cache,
    data_axis,
    get_mesh,
    num_shards,
    simulated_device_env,
)
from ray_tpu.sharding.specs import (
    batch_sharded,
    leaf_sharding,
    replicated,
    shard_batch,
    sharding_tree,
    tree_nbytes,
)
from ray_tpu.sharding.superstep import (
    build_stack_fn,
    build_superstep_fn,
    resolve_superstep,
)


def resolve_mesh(config):
    """The mesh a policy should learn on, per config: an injected
    ``_mesh`` (Algorithm.setup, multi-host tests) wins; otherwise the
    backend decides — ``"mesh"`` builds through this package,
    ``"pmap"`` through the legacy ``ray_tpu.parallel`` adapter (axis
    named ``"data"``), keeping that path byte-compatible."""
    m = config.get("_mesh")
    if m is not None:
        return m
    if config.get("sharding_backend", "mesh") == "pmap":
        from ray_tpu.parallel import mesh as _legacy

        return _legacy.make_mesh()
    return get_mesh()


__all__ = [
    "BATCH_AXIS",
    "MODEL_AXIS",
    "ShardedFunction",
    "available_devices",
    "batch_sharded",
    "build_stack_fn",
    "build_superstep_fn",
    "resolve_superstep",
    "clear_mesh_cache",
    "compile_stats",
    "data_axis",
    "get_mesh",
    "leaf_sharding",
    "num_shards",
    "replicated",
    "resolve_mesh",
    "shard_batch",
    "sharded_jit",
    "sharding_tree",
    "simulated_device_env",
    "tree_nbytes",
]
