"""Superstep builder: K learner updates fused into ONE compiled program.

The Podracer/Anakin lesson applied to this learner plane: the host
boundary (dispatch + stats readback — a full tunnel RTT each on a
remote TPU backend) is crossed once per *superstep* of K updates, not
once per update, so the fixed per-call overhead amortizes 1/K. This
module generalizes what used to be a SAC special case
(``sac.py learn_on_stacked_batch``) into the uniform learner contract:
an outer ``lax.scan`` over any policy's single-update device body.

Mechanics (all inside one ``sharded_jit`` program):

  - the scan carry threads (params, opt_state, aux) — target nets,
    polyak blends, step counters ride the carry; no weights bounce
    through the host between updates. ``opt_state`` is donated.
  - the scan consumes either a **stacked** ``(K, B, ...)`` batch tree
    (PPO's prefetched device batches, host-replay gathers — one H2D
    for the whole superstep) or the **device replay rings in place**:
    host-pre-drawn index arrays ``(K, B)`` ship once per superstep and
    the program gathers each update's rows from the
    ``DeviceReplayBuffer`` store with explicit row-sharded
    out-shardings matching the scan body's batch sharding, so no
    resharding collective fires at the scan-body boundary.
  - the program is compiled once at a static ``K`` with an ``active``
    mask: any ``k_actual <= K`` runs through the SAME executable
    (masked slots pass params through unchanged), so varying chain
    lengths never retrace (``compile_stats()``-asserted).
  - stats stack to ``(K, ...)`` device arrays and drain in ONE
    device→host readback at superstep end; with ``priority_fn`` the
    per-update TD errors for prioritized replay stack to ``(K, B)``
    and ride the same drain.
  - ``nan_guard=True`` moves the non-finite batch guard INSIDE the
    scan body (device-resident batches never pass the host choke
    points in train_ops): a non-finite batch's update is a masked
    no-op and the per-update skip flag lands in the stats tree.

Index draws and rng splits stay HOST-side in the exact per-update call
order (the caller's responsibility — see
``JaxPolicy.learn_superstep``), so a fixed seed produces bit-identical
params/opt-state to K individual learn calls.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ray_tpu.sharding.compile import ShardedFunction, sharded_jit
from ray_tpu.sharding.mesh import data_axis, num_shards
from ray_tpu.sharding.specs import batch_sharded, named_tree, replicated

# stats-tree key for the in-scan nan_guard skip flag (1.0 = the slot's
# update was suppressed because its batch contained non-finite floats)
SKIP_KEY = "superstep_skipped"


def resolve_superstep(config: Dict, mesh=None) -> int:
    """Resolve ``AlgorithmConfig.superstep`` (``"auto" | int``) to the
    K this run fuses per dispatch (1 = off).

    ``"auto"`` engages (K=8) exactly where the amortization pays: a
    mesh-backend learner behind a real accelerator boundary, where the
    per-dispatch RTT is the measured bottleneck (benchmarks/MFU.md).
    On the CPU client dispatch is cheap and the K-step scan is pure
    compile time, so auto resolves off — mirroring
    ``resolve_device_resident``. An explicit int forces that K
    anywhere (tests, benchmarks). The legacy pmap backend keeps
    per-update dispatch."""
    mode = config.get("superstep", "auto")
    if mode in (None, False, 0, 1):
        return 1
    if config.get("sharding_backend", "mesh") != "mesh":
        return 1
    if mode == "auto":
        try:
            devices = (
                mesh.devices.flatten()
                if mesh is not None
                else jax.devices()
            )
            if all(d.platform == "cpu" for d in devices):
                return 1
        except Exception:
            return 1
        return 8
    return max(1, int(mode))


def batch_finite(batch: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Scalar 1.0/0.0: every float column of ``batch`` is NaN/Inf-free
    (the device-side counterpart of ``resilience.recovery
    .batch_is_finite`` — same column selection: floats only)."""
    ok = jnp.float32(1.0)
    for v in jax.tree_util.tree_leaves(batch):
        if jnp.issubdtype(v.dtype, jnp.floating):
            ok = ok * jnp.isfinite(v).all().astype(jnp.float32)
    return ok


def build_superstep_fn(
    update_fn: Callable,
    *,
    mesh,
    backend: str = "mesh",
    k: int,
    label: str,
    stacked_cols: Optional[Sequence[str]] = None,
    replicated_cols: Sequence[str] = (),
    gather_fn: Optional[Callable] = None,
    store_shardings: Optional[Dict] = None,
    extra_cols: Sequence[str] = (),
    rollout_fn: Optional[Callable] = None,
    priority_fn: Optional[Callable] = None,
    nan_guard: bool = False,
    carry_pspecs=None,
) -> ShardedFunction:
    """Compile the K-update superstep program around ``update_fn``.

    ``update_fn(params, opt_state, aux, batch, rng, coeffs) ->
    (params, opt_state, aux, stats)`` is the policy's single-update
    device body (it runs inside ``shard_map``: ``lax.pmean`` etc. are
    available) — the SAME body the per-update learn program wraps, so
    the fused chain is bit-identical to K individual calls.

    Feed modes (mutually exclusive):
      - ``stacked_cols``: the program takes a ``(K, B, ...)`` column
        tree; columns named in ``replicated_cols`` (e.g. the
        deduplicated frame pool) replicate instead of row-sharding.
      - ``gather_fn(store, idx) -> (K, B, ...) tree`` with
        ``store_shardings``: the program takes the device replay rings
        plus a host ``(K, B)`` index array and gathers the batches in
        place; ``extra_cols`` names host-shipped stacked columns
        merged after the gather (PER importance weights).
      - ``rollout_fn(params, carry, rollout_rngs, coeffs) -> (carry,
        batch, metrics)``: each slot PRODUCES its own batch by rolling
        out a JAX-native vectorized env on the mesh
        (``execution/jax_rollout.py``) — rollout(T) + postprocess +
        update fuse into the scan body, so the whole
        rollout+learn superstep is ONE dispatch with zero batch H2D.
        ``carry`` (env state + carried obs, row-sharded) threads
        through the scan alongside the learner state: slot k acts with
        the params slot k-1 produced — the on-policy contract.
        ``metrics`` (per-slot episode-completion arrays, any pytree of
        ``(..., N)`` leaves sharded on the last axis) stack to
        ``(K, ..., N)`` outputs and ride the single stats drain.

    ``priority_fn(params, aux, batch, rng) -> (B,)`` runs after each
    update on the post-update state (per-update PER refresh order) and
    its outputs stack to a ``(K, B)`` program output (stacked/gather
    feeds only).

    Compiled signature::

        fn(params, opt_state, aux, feed, active, rngs[, pri_rngs |
           rollout_rngs], coeffs)
          -> (params, opt_state, aux[, carry], stats[, priorities |
              metrics])

    where ``feed`` is the stacked tree, ``(store, idx, extra)``, or
    the rollout carry; ``active`` is the ``(K,)`` float mask and
    ``rngs`` the host-split ``(K, 2)`` key stack (rollout mode adds
    the ``(K, T, 2)`` rollout key stack). ``opt_state`` is donated.
    """
    if (
        int(stacked_cols is not None)
        + int(gather_fn is not None)
        + int(rollout_fn is not None)
    ) != 1:
        raise ValueError(
            "exactly one of stacked_cols / gather_fn / rollout_fn "
            "must be given"
        )
    if rollout_fn is not None and priority_fn is not None:
        raise ValueError(
            "priority_fn is a replay-feed feature; the rollout feed "
            "is on-policy"
        )
    axis = data_axis(mesh)
    replicated_cols = set(replicated_cols)
    with_pri = priority_fn is not None
    # (params, opt_state, aux) PartitionSpec trees: P() everywhere on
    # the replicated path; per-leaf trees when the policy's params are
    # partitioned over the model axis — the scan carry, donation, and
    # the one compiled executable all preserve them
    if carry_pspecs is None:
        p_ps = o_ps = a_ps = P()
    else:
        p_ps, o_ps, a_ps = carry_pspecs

    if rollout_fn is not None:
        return _build_rollout_superstep(
            update_fn,
            rollout_fn,
            mesh=mesh,
            backend=backend,
            axis=axis,
            label=label,
            nan_guard=nan_guard,
            carry_pspecs=(p_ps, o_ps, a_ps),
        )

    def multi_fn(params, opt_state, aux, stacked, active, *rest):
        if with_pri:
            rngs, pri_rngs, coeffs = rest
            xs = (stacked, active, rngs, pri_rngs)
        else:
            rngs, coeffs = rest
            xs = (stacked, active, rngs)

        def body(carry, x):
            params, opt_state, aux = carry
            if with_pri:
                batch, act, rng, pri_rng = x
            else:
                batch, act, rng = x
            # pin the fusion boundary: the standalone per-update
            # program sees its inputs as opaque parameters, while the
            # scan body would see carries and xs slices XLA may fuse
            # into the update math differently (last-ulp drift on some
            # backends). The barrier makes the body compile like the
            # standalone program, keeping the chain bit-identical to K
            # individual calls.
            params, opt_state, aux, batch, rng = (
                jax.lax.optimization_barrier(
                    (params, opt_state, aux, batch, rng)
                )
            )
            new_p, new_o, new_a, stats = update_fn(
                params, opt_state, aux, batch, rng, coeffs
            )
            ok = act
            if nan_guard:
                # device-resident batches never pass the host nan
                # guard choke points; check inside the scan body and
                # agree across shards (each sees only its row slice)
                fin = jax.lax.pmin(batch_finite(batch), axis)
                ok = ok * fin
                stats = dict(stats, **{SKIP_KEY: 1.0 - fin})
            elif SKIP_KEY not in stats:
                stats = dict(stats, **{SKIP_KEY: jnp.float32(0.0)})

            def keep(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok > 0.5, n, o), new, old
                )

            params = keep(new_p, params)
            opt_state = keep(new_o, opt_state)
            aux = keep(new_a, aux)
            if with_pri:
                # post-update state, matching the per-update path's
                # learn -> compute_td_error -> update_priorities order
                pri = priority_fn(params, aux, batch, pri_rng)
                return (params, opt_state, aux), (stats, pri)
            return (params, opt_state, aux), stats

        # default unroll (a real loop): every iteration — and every
        # (k_actual, slot) combination — runs the ONE compiled body,
        # so splitting a chain across dispatches is bit-identical to
        # fusing it (scan(k)=scan(1)^k through this program), which is
        # what the zero-recompile/all-K-one-program contract promises.
        (params, opt_state, aux), ys = jax.lax.scan(
            body, (params, opt_state, aux), xs
        )
        if with_pri:
            stats, pri = ys
            return params, opt_state, aux, stats, pri
        return params, opt_state, aux, ys

    # per-column shard_map specs for the stacked tree the scan consumes
    if stacked_cols is not None:
        cols = tuple(stacked_cols)
    else:
        cols = tuple(sorted(store_shardings or ())) + tuple(extra_cols)
    stacked_spec = {
        c: (P() if c in replicated_cols else P(None, axis))
        for c in cols
    }
    sm_in = (p_ps, o_ps, a_ps, stacked_spec, P(), P()) + (
        (P(), P()) if with_pri else (P(),)
    )
    sm_out = (p_ps, o_ps, a_ps, P()) + (
        (P(None, axis),) if with_pri else ()
    )
    sharded = jax.shard_map(
        multi_fn, mesh=mesh, in_specs=sm_in, out_specs=sm_out
    )

    dat2 = batch_sharded(mesh, ndim_prefix=2)
    rep = replicated(mesh)

    if gather_fn is not None:

        def program(params, opt_state, aux, feed, active, *rest):
            store, idx, extra = feed
            stacked = dict(gather_fn(store, idx))
            if backend == "mesh":
                # layout-matched gather: emit rows already in the scan
                # body's row-sharded batch layout, so no resharding
                # collective fires at the scan-body boundary
                stacked = {
                    c: jax.lax.with_sharding_constraint(v, dat2)
                    for c, v in stacked.items()
                }
            stacked.update(extra)
            return sharded(
                params, opt_state, aux, stacked, active, *rest
            )

    else:

        def program(params, opt_state, aux, stacked, active, *rest):
            return sharded(
                params, opt_state, aux, stacked, active, *rest
            )

    if backend != "mesh":
        return sharded_jit(
            program, donate_argnums=(1,), label=label
        )
    if gather_fn is not None:
        feed_spec = (
            dict(store_shardings),
            rep,
            {c: dat2 for c in extra_cols},
        )
    else:
        feed_spec = {
            c: (rep if c in replicated_cols else dat2) for c in cols
        }
    p_sh = named_tree(mesh, p_ps)
    o_sh = named_tree(mesh, o_ps)
    a_sh = named_tree(mesh, a_ps)
    in_specs = (p_sh, o_sh, a_sh, feed_spec, rep, rep) + (
        (rep, rep) if with_pri else (rep,)
    )
    out_specs = (p_sh, o_sh, a_sh, rep) + (
        (dat2,) if with_pri else ()
    )
    return sharded_jit(
        program,
        in_specs=in_specs,
        out_specs=out_specs,
        donate_argnums=(1,),
        label=label,
    )


def _build_rollout_superstep(
    update_fn: Callable,
    rollout_fn: Callable,
    *,
    mesh,
    backend: str,
    axis: str,
    label: str,
    nan_guard: bool,
    carry_pspecs=(P(), P(), P()),
) -> ShardedFunction:
    """The rollout-producing feed of :func:`build_superstep_fn`: slot
    k of the scan rolls out the env carry with the CURRENT params,
    builds its train batch in place, and updates — rollout+learn as
    one compiled chain (docs/data_plane.md "fused rollout").

    Masked slots (``active`` 0) revert params/opt/aux AND the env
    carry, so running ``k < k_max`` through the one executable neither
    trains nor advances the envs for the padded slots."""

    def multi_fn(params, opt_state, aux, carry0, active, rngs, ro_rngs, coeffs):
        def body(scan_carry, x):
            params, opt_state, aux, env_carry = scan_carry
            act, rng, ro_rng = x
            # same fusion-boundary pin as the batch feeds: the body
            # compiles like the standalone rollout + update programs,
            # keeping the fused chain bit-identical to dispatching the
            # pieces separately
            params, opt_state, aux, env_carry, rng, ro_rng = (
                jax.lax.optimization_barrier(
                    (params, opt_state, aux, env_carry, rng, ro_rng)
                )
            )
            new_carry, batch, metrics = rollout_fn(
                params, env_carry, ro_rng, coeffs
            )
            new_p, new_o, new_a, stats = update_fn(
                params, opt_state, aux, batch, rng, coeffs
            )
            ok = act
            if nan_guard:
                fin = jax.lax.pmin(batch_finite(batch), axis)
                ok = ok * fin
                stats = dict(stats, **{SKIP_KEY: 1.0 - fin})
            elif SKIP_KEY not in stats:
                stats = dict(stats, **{SKIP_KEY: jnp.float32(0.0)})

            def keep(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok > 0.5, n, o), new, old
                )

            params = keep(new_p, params)
            opt_state = keep(new_o, opt_state)
            aux = keep(new_a, aux)
            # a nan-guarded slot keeps its ROLLOUT (those env steps
            # happened; the host counts them) but reverts the update;
            # only an INACTIVE slot reverts the env advance
            env_carry = jax.tree_util.tree_map(
                lambda n, o: jnp.where(act > 0.5, n, o),
                new_carry,
                env_carry,
            )
            return (params, opt_state, aux, env_carry), (stats, metrics)

        (params, opt_state, aux, carry0), (stats, metrics) = (
            jax.lax.scan(
                body,
                (params, opt_state, aux, carry0),
                (active, rngs, ro_rngs),
            )
        )
        return params, opt_state, aux, carry0, stats, metrics

    # carry leaves are per-env rows (leading dim N); metrics leaves
    # end in the env dim (engine contract) so they shard on axis -1
    p_ps, o_ps, a_ps = carry_pspecs
    sharded = jax.shard_map(
        multi_fn,
        mesh=mesh,
        in_specs=(p_ps, o_ps, a_ps, P(axis), P(), P(), P(), P()),
        out_specs=(
            p_ps,
            o_ps,
            a_ps,
            P(axis),
            P(),
            P(*([None] * 2 + [axis])),
        ),
    )
    if backend != "mesh":
        return sharded_jit(
            sharded, donate_argnums=(1,), label=label
        )
    rep = replicated(mesh)
    dat = batch_sharded(mesh)
    met = batch_sharded(mesh, ndim_prefix=3)
    p_sh = named_tree(mesh, p_ps)
    o_sh = named_tree(mesh, o_ps)
    a_sh = named_tree(mesh, a_ps)
    return sharded_jit(
        sharded,
        in_specs=(p_sh, o_sh, a_sh, dat, rep, rep, rep, rep),
        out_specs=(p_sh, o_sh, a_sh, dat, rep, met),
        donate_argnums=(1,),
        label=label,
    )


def build_stack_fn(mesh, k: int, label: str) -> ShardedFunction:
    """Compile the device-side stacker turning ``k`` already-resident
    ``(B, ...)`` batch trees into one ``(k, B, ...)`` superstep feed
    (PPO's prefetched batches, the IMPALA learner queue) — a pure
    device reshuffle, no host round trip."""
    def stack(*trees):
        return jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *trees
        )

    return sharded_jit(
        stack,
        out_specs=batch_sharded(mesh, ndim_prefix=2),
        label=label,
    )
