"""Mesh construction for the sharding runtime.

One mesh per process (cached), built from whatever devices the backend
exposes: real TPU cores, a CPU fallback, or simulated host devices via
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (the standard
way to test multi-device layouts without hardware — tests/conftest.py
forces 8).

Axis conventions:
  - ``"batch"``: data parallelism over the train-batch leading dim —
    the only axis the learner uses today.
  - ``"model"``: reserved for tensor parallelism of large learner
    models (multi-chip PRs add shapes here; the name is fixed now so
    specs written against it won't churn).

The legacy ``ray_tpu.parallel.mesh`` module is an adapter over this one
and keeps its historical ``"data"`` axis name for the pmap-path
programs; everything here derives the axis from the mesh object, so
both namings interoperate.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

BATCH_AXIS = "batch"
MODEL_AXIS = "model"

# (device ids, axis names, axis sizes) -> Mesh. Mesh construction is
# cheap but identity matters: jit caches key on sharding objects, and
# two equal-but-distinct meshes would recompile every learn program.
_MESH_CACHE: dict = {}


def available_devices(platform: Optional[str] = None):
    """Devices to build meshes from. ``platform`` filters ("tpu",
    "cpu"); when the requested platform has no devices the CPU host
    devices are the fallback, so a learner configured for TPU still
    comes up (slowly) on a dev box."""
    devs = jax.devices()
    if platform:
        matched = [d for d in devs if d.platform == platform]
        if matched:
            return matched
        devs = [d for d in jax.devices() if d.platform == "cpu"] or devs
    return devs


def get_mesh(
    devices=None,
    axis_shapes: Optional[Sequence[Tuple[str, int]]] = None,
    platform: Optional[str] = None,
) -> Mesh:
    """Build (or fetch the cached) mesh.

    Default shape is a 1-D ``("batch",)`` data mesh over all available
    devices — simulated host devices from
    ``--xla_force_host_platform_device_count`` count like real ones.
    ``axis_shapes`` opts into richer layouts, e.g.
    ``[("batch", 4), ("model", 2)]``.
    """
    if devices is None:
        devices = available_devices(platform)
    devices = list(devices)
    if axis_shapes is None:
        axis_shapes = [(BATCH_AXIS, len(devices))]
    names = tuple(n for n, _ in axis_shapes)
    shape = tuple(int(s) for _, s in axis_shapes)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            f"mesh shape {dict(axis_shapes)} needs {n} devices, "
            f"have {len(devices)}"
        )
    key = (tuple(id(d) for d in devices[:n]), names, shape)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        mesh = Mesh(np.asarray(devices[:n]).reshape(shape), names)
        _MESH_CACHE[key] = mesh
    return mesh


def clear_mesh_cache() -> None:
    _MESH_CACHE.clear()


def data_axis(mesh: Mesh) -> str:
    """The data-parallel axis of a mesh: its first axis. Works for
    both the new ``("batch",)`` and the legacy ``("data",)`` naming —
    learn programs must use this instead of a string literal."""
    return mesh.axis_names[0]


def num_shards(mesh: Mesh) -> int:
    return int(mesh.shape[data_axis(mesh)])


def model_axis(mesh: Mesh) -> Optional[str]:
    """The tensor-parallel axis name when the mesh carries one
    (2-D data x model layouts built by ``resolve_mesh`` with
    ``model_parallel`` set), else None. Presence — even at size 1 —
    activates per-leaf param placement in the learn programs; size 1
    keeps every leaf whole (the parity geometry)."""
    return MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None


def model_shards(mesh: Mesh) -> int:
    """Size of the model axis (1 when the mesh has none)."""
    if MODEL_AXIS in mesh.axis_names:
        return int(mesh.shape[MODEL_AXIS])
    return 1


def resolve_model_parallel(config, devices=None, strict: bool = False) -> int:
    """Resolve ``AlgorithmConfig.model_parallel`` (None | "auto" |
    int) to the model-axis size M of this run's mesh.

    Returns 0 when unset — the legacy 1-D data mesh, no model axis at
    all — so existing runs are untouched. Any non-zero M (including
    an explicit 1) builds the 2-D ``[("batch", D//M), ("model", M)]``
    mesh and routes params through the per-leaf rule placement.
    ``"auto"`` resolves to 1 on the CPU client (tensor parallelism
    buys nothing without an accelerator memory wall) and to 2 behind
    a real accelerator when the device count is even."""
    mode = config.get("model_parallel")
    if mode in (None, False, 0):
        return 0
    if devices is None:
        devices = jax.devices()
    n = len(list(devices))
    if mode == "auto":
        try:
            if all(d.platform == "cpu" for d in devices):
                return 1
        except Exception:
            return 1
        return 2 if (n >= 2 and n % 2 == 0) else 1
    m = int(mode)
    if m < 1:
        return 0 if m == 0 else 1
    if n % m:
        if strict:
            raise ValueError(
                f"model_parallel={m} does not divide the {n} learner "
                "devices"
            )
        # non-strict callers (rollout workers resolving their own
        # 1-device CPU mesh from the shipped config) degrade to the
        # 1-D data mesh — inference replicas never split params
        return 0
    return m


def resolve_hosts(config, strict: bool = False) -> int:
    """Resolve ``AlgorithmConfig.hosts`` (None | "auto" | int) to the
    number of jax processes the learner mesh spans.

    Returns 1 when unset — the single-process mesh, unchanged
    behavior. ``"auto"`` adopts however many processes the
    jax.distributed runtime brought up (``dist.initialize`` ran first
    in Algorithm.setup). An explicit N asserts the runtime actually
    spans N processes when ``strict`` — a mesh silently smaller than
    the config promised is the hardest multi-host bug to notice."""
    mode = config.get("hosts")
    if mode in (None, False, 0):
        return 1
    if mode == "auto":
        return int(jax.process_count())
    h = int(mode)
    if h < 1:
        return 1
    if strict and h != jax.process_count():
        raise ValueError(
            f"sharding(hosts={h}) but the jax runtime spans "
            f"{jax.process_count()} process(es) — set "
            "RAY_TPU_COORDINATOR/RAY_TPU_NUM_PROCESSES/"
            "RAY_TPU_PROCESS_ID (or hosts='auto') so the fleet "
            "geometry and the runtime agree"
        )
    return h


def global_devices(hosts: int):
    """The devices a ``hosts``-process learner mesh is built from:
    every process's devices when the mesh spans hosts (the DCN × ICI
    global view — XLA routes collectives over ICI within a host and
    DCN across), this process's local devices otherwise."""
    if hosts > 1:
        return list(jax.devices())
    return list(jax.local_devices())


def simulated_device_env(n: int) -> dict:
    """Env-var dict that makes a fresh process expose ``n`` simulated
    CPU devices (must be set before jax initializes its backend; use
    for subprocess tests and docs examples)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    return {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags}
