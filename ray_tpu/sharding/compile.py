"""``sharded_jit``: the compile layer of the sharding runtime.

Wraps ``jax.jit`` with explicit input/output shardings and buffer
donation (the modern spelling of the retrieved ``pjit`` pattern:
``in_axis_resources``/``donate_argnums``), and instruments the compile
cache: every retrace is counted and its wall time recorded, so "did
this step recompile?" is a metric instead of a profiler session.

Both learner backends come through here — the ``mesh`` backend with
``NamedSharding`` trees attached, the legacy ``pmap`` fallback as a
plain jit — so compile stats cover the whole learner plane either way.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import weakref
from typing import Any, Dict, Optional, Sequence, Tuple

import jax

from ray_tpu.telemetry import device as device_ledger
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing

_LOCK = threading.Lock()
# live ShardedFunctions, for process-wide stats aggregation
_REGISTRY: "weakref.WeakSet" = weakref.WeakSet()

# -- dispatch diet (benchmarks/MFU.md "dispatch overhead") -------------
#
# Once a program is superstep-small, the per-call host work around the
# actual XLA dispatch is the learner critical path. The diet arms a
# steady-state fast path in ``ShardedFunction.__call__`` (one
# perf-clock pair, no lock, no ledger/tracing hooks) plus the cached
# NamedSharding trees in specs.py and the fused host rng chains in
# jax_policy.py. ``RAY_TPU_DISPATCH_DIET=0`` restores the pre-diet
# bookkeeping on every call — the A/B side ``bench.py --dispatch``
# measures against.
_DIET = os.environ.get("RAY_TPU_DISPATCH_DIET", "1").lower() not in (
    "0", "false", "off",
)


def dispatch_diet_enabled() -> bool:
    return _DIET


def set_dispatch_diet(on: bool) -> bool:
    """Flip the diet at runtime (tests, the --dispatch A/B). Returns
    the previous setting."""
    global _DIET
    prev = _DIET
    _DIET = bool(on)
    return prev


def _mesh_geometry_token(tree) -> Tuple:
    """Stable token naming every mesh geometry the tree's shardings
    reference: ((axis, size) pairs, participating device ids) per
    distinct mesh. The AOT cache keys on it (aot.FORMAT 2) — a program
    lowered on a 2-host (dcn=2, batch=k) mesh and its 1-host resize
    twin share label AND abstract shapes but not executables, so the
    geometry must be part of the entry identity for the fleet's
    pre-seeded ±1-host entries to coexist."""
    toks = set()
    for leaf in jax.tree_util.tree_leaves(tree):
        for holder in (leaf, getattr(leaf, "sharding", None)):
            mesh = getattr(holder, "mesh", None)
            if mesh is None:
                continue
            try:
                axes = tuple(
                    (str(a), int(s))
                    for a, s in dict(mesh.shape).items()
                )
                ids = tuple(
                    int(d.id) for d in mesh.devices.flat
                )
            except Exception:
                continue
            toks.add((axes, ids))
    return tuple(sorted(toks))


class ShardedFunction:
    """A compiled, partitioned callable.

    Callable like the underlying jitted function. ``stats()`` reports
    the compile-cache behavior:

      - ``traces``: distinct (shape, dtype, static-arg) signatures
        compiled so far — 1 after warmup means shape-stable;
      - ``recompiles``: traces beyond the first (should be 0 across
        steps with constant shapes);
      - ``calls``: total invocations;
      - ``compile_time_s``: wall time of the calls that traced
        (compile + first dispatch); steady-state calls add nothing.
    """

    def __init__(
        self,
        fn,
        in_specs=None,
        out_specs=None,
        donate_argnums: Sequence[int] = (),
        static_argnames: Sequence[str] = (),
        label: Optional[str] = None,
    ):
        self.label = label or getattr(fn, "__name__", "sharded_fn")
        self.traces = 0
        self.calls = 0
        self.compile_time_s = 0.0
        # AOT-installed dispatch path (sharding/aot.py): a compiled
        # executable restored from the persistent cache ("aot_cache")
        # or compiled ahead of time here ("aot_live"); None = plain jit
        self._aot = None
        self.aot_source: Optional[str] = None
        self.aot_fallbacks = 0
        # ledger-visible program identity (telemetry/device.py)
        self.in_specs = in_specs
        self.out_specs = out_specs
        self.donate_argnums = tuple(donate_argnums)
        self.static_argnames = tuple(static_argnames)
        # donation pre-validation, ONCE at wrap time: jax re-checks the
        # donate/static interaction on every trace, but a donate index
        # that is not a non-negative int (or collides with nothing it
        # could ever donate) is a wiring bug worth failing at
        # construction, not at first dispatch
        for i in self.donate_argnums:
            if not isinstance(i, int) or i < 0:
                raise ValueError(
                    f"donate_argnums must be non-negative ints, got "
                    f"{self.donate_argnums!r} for {self.label!r}"
                )
        self._lock = threading.Lock()
        self._uncounted = threading.local()

        def _counted(*args, **kwargs):
            # the ledger's ahead-of-time analysis compile re-traces
            # abstractly; that must not count as a (re)trace of the
            # execution path
            if not getattr(self._uncounted, "on", False):
                with self._lock:
                    self.traces += 1
            return fn(*args, **kwargs)

        kw: Dict[str, Any] = {}
        if in_specs is not None:
            kw["in_shardings"] = in_specs
        if out_specs is not None:
            kw["out_shardings"] = out_specs
        if static_argnames:
            kw["static_argnames"] = tuple(static_argnames)
        if donate_argnums:
            kw["donate_argnums"] = tuple(donate_argnums)
        self._jitted = jax.jit(_counted, **kw)
        with _LOCK:
            _REGISTRY.add(self)

    @contextlib.contextmanager
    def uncounted_traces(self):
        """Scope in which re-traces don't bump ``traces`` (the device
        ledger's AOT analysis compile — same function, abstract args)."""
        self._uncounted.on = True
        try:
            yield
        finally:
            self._uncounted.on = False

    def aot_warmup(self, cache, *args, **kwargs) -> str:
        """Install an ahead-of-time compiled executable for the ONE
        abstract signature ``(*args, **kwargs)`` describes (the serve
        bucket contract: one ShardedFunction = one static shape).

        Tries the persistent cache first — a hit installs the
        deserialized executable with ZERO fresh compiles and registers
        it in the device ledger with ``compile_s=0`` /
        ``source="aot_cache"``. A miss compiles ahead of time (counted
        as this function's one trace), installs the result, and queues
        the serialized executable for the cache writer so the NEXT
        replica hits. Returns ``"hit"`` / ``"compiled"`` /
        ``"disabled"`` (no cache, or a jax build that can't serialize
        executables — the caller falls back to plain jit warmup).

        The cache signature carries the MESH GEOMETRY of the program's
        shardings on top of the ledger's shape/dtype signature: the
        same label at the same shapes lowers to different collectives
        on different meshes (a 2-host fleet pre-seeding its 1-host
        resize geometry is the motivating case — without the token the
        two entries would collide on one key).
        """
        from ray_tpu.sharding import aot as aot_lib

        cache = aot_lib.resolve_cache(cache)
        if cache is None or not aot_lib.supported():
            return "disabled"
        try:
            sig = device_ledger.signature_of(
                args, kwargs, self.static_argnames
            )
            geo = _mesh_geometry_token(
                (args, kwargs, self.in_specs, self.out_specs)
            )
            if geo:
                sig = (sig, ("mesh", geo))
        except Exception:
            return "disabled"
        loaded = cache.load(self.label, sig)
        if loaded is not None:
            self._aot = loaded
            self.aot_source = "aot_cache"
            device_ledger.on_aot(self, 0.0, "aot_cache")
            return "hit"
        t0 = time.perf_counter()
        try:
            with self.uncounted_traces():
                compiled = self._jitted.lower(
                    *args, **kwargs
                ).compile()
        except Exception:
            return "disabled"
        dt = time.perf_counter() - t0
        with self._lock:
            # a real XLA compile: count it exactly like a jit trace so
            # compile_stats stays honest about cold-start cost
            self.traces += 1
            self.compile_time_s += dt
        self._aot = compiled
        self.aot_source = "aot_live"
        device_ledger.on_aot(self, dt, "aot_live")
        cache.save(self.label, sig, compiled)
        return "compiled"

    def _call_aot(self, args, kwargs):
        """Dispatch through the installed AOT executable; any failure
        (signature drift, an executable a stale cache slipped past the
        keying) drops the AOT path and falls back to plain jit — the
        graceful-fallback contract. Shape/dtype mismatches raise
        BEFORE execution, so donated buffers are still intact for the
        fallback call."""
        ledger_on = device_ledger.enabled()
        trace_on = tracing.is_enabled()
        if not (ledger_on or trace_on):
            # steady-path diet: nobody consumes the wall/perf stamps,
            # so don't take them (the ledger hook below early-returns)
            try:
                out = self._aot(*args, **kwargs)
            except Exception:
                self._aot = None
                with self._lock:
                    self.aot_fallbacks += 1
                tracing.event("aot:fallback", label=self.label)
                try:
                    telemetry_metrics.inc_aot_cache_event("fallback")
                except Exception:
                    pass
                return None
            self.calls += 1
            return (out,)
        t_wall0 = time.time()
        t0 = time.perf_counter()
        try:
            if trace_on:
                with tracing.start_span("jit:" + self.label) as sp:
                    out = self._aot(*args, **kwargs)
                    sp.set_attribute("aot", self.aot_source)
            else:
                out = self._aot(*args, **kwargs)
        except Exception:
            self._aot = None
            with self._lock:
                self.aot_fallbacks += 1
            tracing.event("aot:fallback", label=self.label)
            try:
                telemetry_metrics.inc_aot_cache_event("fallback")
            except Exception:
                pass
            return None
        dt = time.perf_counter() - t0
        with self._lock:
            self.calls += 1
        device_ledger.on_call(self, t_wall0, dt, traced=False)
        return (out,)

    def __call__(self, *args, **kwargs):
        if self._aot is not None:
            boxed = self._call_aot(args, kwargs)
            if boxed is not None:
                return boxed[0]
        before = self.traces
        # dispatch-diet fast path (bench.py --dispatch): after warmup,
        # with neither tracing nor the device ledger consuming the
        # per-call stamps, dispatch costs one perf-clock pair and an
        # unlocked counter bump — no time.time(), no lock, no span, no
        # ledger hook. A retrace detected after the fact (shape drift,
        # a genuinely changed sharding) falls back to the full
        # bookkeeping below for THIS call, so compile stats and
        # forensics stay exact on every path that compiles.
        if (
            _DIET
            and before > 0
            and not tracing.is_enabled()
            and not device_ledger.enabled()
        ):
            t0 = time.perf_counter()
            out = self._jitted(*args, **kwargs)
            if self.traces == before:
                self.calls += 1
                return out
            dt = time.perf_counter() - t0
            device_ledger.on_traced(self, args, kwargs, dt)
            with self._lock:
                self.calls += 1
                self.compile_time_s += dt
            return out
        t_wall0 = time.time()
        t0 = time.perf_counter()
        if tracing.is_enabled():
            # trace-vs-cached-execute span: "did this step recompile?"
            # shows up as a lane in the chrome trace, and a retrace
            # after warmup additionally records a recompile event —
            # with the ledger on, carrying the forensics cause (which
            # abstract leaf's shape/dtype moved)
            with tracing.start_span("jit:" + self.label) as sp:
                out = self._jitted(*args, **kwargs)
                traced = self.traces != before
                sp.set_attribute("traced", traced)
                if traced:
                    cause = device_ledger.on_traced(
                        self, args, kwargs,
                        time.perf_counter() - t0,
                    )
                    if before > 0:
                        ev = {"label": self.label}
                        if cause:
                            ev["cause"] = cause
                        tracing.event("jit:recompile", **ev)
        else:
            out = self._jitted(*args, **kwargs)
            if self.traces != before:
                device_ledger.on_traced(
                    self, args, kwargs, time.perf_counter() - t0
                )
        dt = time.perf_counter() - t0
        with self._lock:
            self.calls += 1
            if self.traces != before:
                self.compile_time_s += dt
        device_ledger.on_call(
            self, t_wall0, dt, traced=self.traces != before
        )
        return out

    @property
    def recompiles(self) -> int:
        return max(0, self.traces - 1)

    def stats(self) -> Dict[str, Any]:
        out = {
            "label": self.label,
            "traces": self.traces,
            "recompiles": self.recompiles,
            "calls": self.calls,
            "compile_time_s": self.compile_time_s,
        }
        if self.aot_source is not None or self.aot_fallbacks:
            out["aot_source"] = self.aot_source
            out["aot_fallbacks"] = self.aot_fallbacks
        return out

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)


def sharded_jit(
    fn,
    in_specs=None,
    out_specs=None,
    donate_argnums: Sequence[int] = (),
    static_argnames: Sequence[str] = (),
    label: Optional[str] = None,
) -> ShardedFunction:
    """Compile ``fn`` partitioned across the mesh its shardings name.

    ``in_specs``/``out_specs`` are per-argument shardings (a single
    ``NamedSharding`` broadcasts over that argument's pytree leaves);
    ``None`` leaves placement to jit (the legacy-fallback mode).
    ``donate_argnums`` releases those input buffers to the output —
    opt-state double-buffering for free."""
    return ShardedFunction(
        fn,
        in_specs=in_specs,
        out_specs=out_specs,
        donate_argnums=donate_argnums,
        static_argnames=static_argnames,
        label=label,
    )


def f64_scope():
    """The x64 scope the device segment-tree programs build and run
    in (``ops/segment_tree.DeviceSumTree``). Priorities are float64
    state — the host sum tree the device tree must reproduce
    bit-exactly is numpy f64 — but this process keeps jax's default
    x64-off canonicalization for every learner program. The scope is
    thread-local and wraps ONLY the tree programs: their f64 arrays
    stay f64 across calls (a jit traced outside the scope would
    silently downcast them to f32), while their f32/i32 outputs (IS
    weights, drawn indices) feed the ordinary f32 learner world
    outside."""
    from jax.experimental import enable_x64

    return enable_x64()


def compile_stats() -> Dict[str, Any]:
    """Process-wide compile-cache summary across every live
    ShardedFunction (benchmarks and the acceptance test read this)."""
    with _LOCK:
        fns = list(_REGISTRY)
    per_fn = [f.stats() for f in fns]
    return {
        "functions": len(per_fn),
        "traces": sum(s["traces"] for s in per_fn),
        "recompiles": sum(s["recompiles"] for s in per_fn),
        "calls": sum(s["calls"] for s in per_fn),
        "compile_time_s": sum(s["compile_time_s"] for s in per_fn),
        "per_function": per_fn,
        # forensics rollup (telemetry/device.py): per-label recompile
        # causes — the abstract-signature diffs of every retrace seen
        # while the device ledger ran ({} with the ledger off)
        "recompile_causes": device_ledger.recompile_causes(),
    }
