"""``ray_tpu.analysis`` — whole-program device-contract analyzer.

An AST-based rule engine encoding the repo's device contracts
(docs/static_analysis.md has the catalog and the originating bug for
each rule). v2 runs on a repo-wide symbol table + call graph with a
global device/thread/f64 fixed point and a local device-taint pass
(:mod:`ray_tpu.analysis.program`), so cross-module chains — router
batcher → server submit, streamer thread → atomic writer — are
checkable.

==========  ============================================================
RTA001      use-after-donate: a tree donated to a ``sharded_jit``
            program read again (directly or via a local alias) before
            reassignment
RTA002      trace hazards: host numpy / ``.item()`` / coercions inside
            device contexts; bare Python scalars fed to cached programs
RTA003      weak-type promotion: bare float literals in f64 scopes
            (the PR-11 ``|td|+1e-6`` divergence class)
RTA004      RNG discipline: global ``np.random.*`` in library code;
            PRNG keys consumed twice without split/fold_in
RTA005      host sync in hot paths: blocking D2H (explicit primitives
            AND taint-tracked implicit coercions) outside the counted
            drain helpers
RTA006      thread ownership: cross-thread calls between
            ``# ray-tpu: thread=<owner>``-annotated surfaces
RTA007      blocking call reachable from the event loop (async defs /
            ``thread=*-loop`` owners, over the call graph)
RTA008      lock-order inversions collected across the call graph
RTA009      durability: ``os.replace`` outside the atomic-write
            helper, unfsynced renames, raw checkpoint opens
RTA010      metric/span catalog consistency against
            docs/observability.md (names AND label sets)
RTA011      host-RNG draws under device-taint-derived conditionals
            (draw-count determinism)
RTA012      AlgorithmConfig knob reachability + docs/API.md index
==========  ============================================================

Run ``python -m ray_tpu.analysis`` (pure AST — works without jax);
``--since REV`` scans changed files + reverse call-graph dependents;
CI gates on zero unbaselined findings via
``tests/test_static_analysis.py``.
"""

from ray_tpu.analysis.engine import (  # noqa: F401
    SCHEMA_VERSION,
    Finding,
    ModuleModel,
    ScanResult,
    default_baseline_path,
    load_baseline,
    save_baseline,
    scan_paths,
)
from ray_tpu.analysis.program import (  # noqa: F401
    ProgramModel,
    TaintInfo,
)
