"""``ray_tpu.analysis`` — device-contract static analyzer.

An AST-based rule engine encoding the repo's device contracts
(docs/static_analysis.md has the catalog and the originating bug for
each rule):

==========  ============================================================
RTA001      use-after-donate: a tree donated to a ``sharded_jit``
            program read again before reassignment
RTA002      trace hazards: host numpy / ``.item()`` / coercions inside
            device contexts; bare Python scalars fed to cached programs
RTA003      weak-type promotion: bare float literals in f64 scopes
            (the PR-11 ``|td|+1e-6`` divergence class)
RTA004      RNG discipline: global ``np.random.*`` in library code;
            PRNG keys consumed twice without split/fold_in
RTA005      host sync in hot paths: blocking D2H outside the counted
            drain helpers in superstep/serve/learner-thread spans
RTA006      thread ownership: cross-thread calls between
            ``# ray-tpu: thread=<owner>``-annotated surfaces
==========  ============================================================

Run ``python -m ray_tpu.analysis`` (pure AST — works without jax);
CI gates on zero unbaselined findings via
``tests/test_static_analysis.py``.
"""

from ray_tpu.analysis.engine import (  # noqa: F401
    Finding,
    ModuleModel,
    ScanResult,
    default_baseline_path,
    load_baseline,
    save_baseline,
    scan_paths,
)
