"""RTA002 — trace hazards in device contexts.

A device-context function's body executes at TRACE time: its array
arguments are tracers, so host numpy calls, ``.item()`` /
``.tolist()``, ``bool()/float()/int()`` coercions, and blocking
device syncs either crash (ConcretizationTypeError) or silently bake
a stale host value into the compiled program. The flip side of the
same contract: host call sites must not feed bare Python scalars to
cached programs — a weak-typed scalar changes the lowered signature
and retraces (the zero-recompile contract; callers wrap scalars as
``np.int32(n)`` / ``np.float64(beta)``).

Static-shape helpers (``np.prod`` over a shape tuple) are legitimate
trace-time host work — suppress with ``# ray-tpu: allow[RTA002]`` and
a reason where used deliberately.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ray_tpu.analysis.engine import Finding, ModuleModel
from ray_tpu.analysis.rules._common import call_name, own_nodes

RULE_ID = "RTA002"

_NP_ROOTS = {"np", "numpy", "np_", "onp"}
# dtype constructors / metadata are concrete trace-time constants
_NP_ALLOWED = {
    "float16", "float32", "float64", "int8", "int16", "int32",
    "int64", "uint8", "uint16", "uint32", "uint64", "bool_",
    "dtype", "ndim", "shape",
}
_SYNC_METHODS = {"item", "tolist", "numpy", "block_until_ready"}
_COERCIONS = {"bool", "float", "int"}

# -- trace-time-static expressions ------------------------------------
# Shapes, dtypes, and config dicts are CONCRETE during tracing:
# `int(np.prod(v.shape[1:]))` or `float(cfg.get("v_min"))` inside a
# device body is host math on static values, not a tracer hazard.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes"}
_CONFIG_NAMES = {"cfg", "config", "hps", "self"}
_STATIC_CALLS = {
    "get", "len", "prod", "int", "float", "bool", "min", "max",
    "bit_length", "range",
}


def _is_trace_static(node: ast.AST) -> bool:
    if node is None or isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS or node.attr == "config":
            return True
        # self.* / cfg.* reads in a traced body are static Python
        # state (traced arrays arrive through the arguments)
        return (
            isinstance(node.value, ast.Name)
            and node.value.id in _CONFIG_NAMES
        )
    if isinstance(node, ast.Name):
        return node.id in _CONFIG_NAMES
    if isinstance(node, ast.Call):
        last = call_name(node).split(".")[-1]
        if last not in _STATIC_CALLS:
            return False
        base_ok = True
        if isinstance(node.func, ast.Attribute):
            base_ok = _is_trace_static(node.func.value) or (
                isinstance(node.func.value, ast.Name)
                and node.func.value.id in _NP_ROOTS | _CONFIG_NAMES
            )
        return base_ok and all(
            _is_trace_static(a) for a in node.args
        )
    if isinstance(node, ast.BinOp):
        return _is_trace_static(node.left) and _is_trace_static(
            node.right
        )
    if isinstance(node, ast.UnaryOp):
        return _is_trace_static(node.operand)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_trace_static(e) for e in node.elts)
    if isinstance(node, ast.Subscript):
        return _is_trace_static(node.value)
    if isinstance(node, ast.Slice):
        return all(
            _is_trace_static(p)
            for p in (node.lower, node.upper, node.step)
        )
    if isinstance(node, ast.Compare):
        return _is_trace_static(node.left) and all(
            _is_trace_static(c) for c in node.comparators
        )
    if isinstance(node, ast.IfExp):
        return all(
            _is_trace_static(p)
            for p in (node.test, node.body, node.orelse)
        )
    return False


def _np_call(call: ast.Call) -> Optional[str]:
    parts = call_name(call).split(".")
    if len(parts) >= 2 and parts[0] in _NP_ROOTS:
        return parts[-1]
    return None


def _compiled_locals(fi) -> Dict[str, str]:
    """Local names bound to compiled programs within this function:
    assigned from ``sharded_jit(...)`` / ``*.sharded_jit(...)`` /
    ``self._build_*(...)`` / ``build_superstep_fn(...)``."""
    out: Dict[str, str] = {}
    for node in own_nodes(fi):
        if not isinstance(node, ast.Assign) or not isinstance(
            node.value, ast.Call
        ):
            continue
        last = call_name(node.value).split(".")[-1]
        if last == "sharded_jit" or (
            last.startswith("_build_") and last.endswith("_fn")
        ) or last in ("build_superstep_fn", "build_stack_fn"):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = last
    return out


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []

    def add(node, msg):
        f = model.finding(RULE_ID, node, msg)
        if f:
            findings.append(f)

    for fi in model.funcs:
        if fi.device:
            for node in own_nodes(fi):
                if not isinstance(node, ast.Call):
                    continue
                np_attr = _np_call(node)
                if (
                    np_attr is not None
                    and np_attr not in _NP_ALLOWED
                    and not all(
                        _is_trace_static(a) for a in node.args
                    )
                ):
                    add(
                        node,
                        f"host `np.{np_attr}` call inside a device "
                        "context — numpy cannot consume tracers; use "
                        "jnp or hoist to the host caller",
                    )
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_METHODS
                ):
                    add(
                        node,
                        f"`.{node.func.attr}()` inside a device "
                        "context forces a concrete value mid-trace",
                    )
                    continue
                name = call_name(node)
                if name.split(".")[-1] == "device_get":
                    add(
                        node,
                        "`jax.device_get` inside a device context — "
                        "D2H mid-trace is a concretization error",
                    )
                    continue
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _COERCIONS
                    and len(node.args) == 1
                    and not _is_trace_static(node.args[0])
                ):
                    add(
                        node,
                        f"`{node.func.id}(...)` coercion inside a "
                        "device context concretizes a traced value "
                        "(Python-value branching retraces per value)",
                    )
        else:
            # host side of the contract: scalar feeds to cached
            # programs retrace per dtype/weak-type signature
            compiled = _compiled_locals(fi)
            if not compiled:
                continue
            for node in own_nodes(fi):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in compiled
                ):
                    for arg in node.args:
                        if isinstance(arg, ast.Constant) and isinstance(
                            arg.value, (int, float)
                        ) and not isinstance(arg.value, bool):
                            add(
                                arg,
                                f"bare Python scalar {arg.value!r} fed "
                                f"to cached program `{node.func.id}` — "
                                "wrap with an explicit np dtype "
                                "(np.int32/np.float64) so the traced "
                                "signature is stable (zero-recompile "
                                "contract)",
                            )
    return findings
