"""Rule registry for the device-contract analyzer.

Each rule module exposes ``RULE_ID`` and one of:

- ``check(model: ModuleModel) -> List[Finding]`` — runs once per
  module (and benefits from the whole-program facts the engine
  propagates onto ``FuncInfo`` before rules run);
- ``check_program(program: ProgramModel) -> List[Finding]`` — runs
  once per scan with the full symbol table / call graph / taint
  machinery (the RTA007+ rule pack).
"""

from __future__ import annotations

from typing import List

from ray_tpu.analysis.rules import (
    catalog,
    donation,
    dtype,
    durability,
    eventloop,
    hostsync,
    knobs,
    kvretry,
    lockorder,
    rng,
    rng_order,
    threads,
    trace,
)

_ALL = [
    donation,
    trace,
    dtype,
    rng,
    hostsync,
    threads,
    eventloop,
    lockorder,
    durability,
    catalog,
    rng_order,
    knobs,
    kvretry,
]

RULE_DOCS = {
    mod.RULE_ID: (mod.__doc__ or "").strip().splitlines()[0]
    for mod in _ALL
}


def all_rules() -> List:
    return list(_ALL)


def rules_by_id(ids) -> List:
    want = {i.upper() for i in ids}
    return [m for m in _ALL if m.RULE_ID in want]
