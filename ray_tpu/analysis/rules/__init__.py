"""Rule registry for the device-contract analyzer.

Each rule module exposes ``RULE_ID`` and
``check(model: ModuleModel) -> List[Finding]``.
"""

from __future__ import annotations

from typing import List

from ray_tpu.analysis.rules import (
    donation,
    dtype,
    hostsync,
    rng,
    threads,
    trace,
)

_ALL = [donation, trace, dtype, rng, hostsync, threads]

RULE_DOCS = {
    mod.RULE_ID: (mod.__doc__ or "").strip().splitlines()[0]
    for mod in _ALL
}


def all_rules() -> List:
    return list(_ALL)


def rules_by_id(ids) -> List:
    want = {i.upper() for i in ids}
    return [m for m in _ALL if m.RULE_ID in want]
