"""RTA006 — thread-ownership violations.

The elastic/serving machinery splits work across long-lived threads
with strict ownership (docs/resilience.md, docs/serving.md): the
FleetController's monitor thread OBSERVES and queues, only the driver
thread's ``reconcile()`` ACTS; the CheckpointStreamer's driver-side
``offer()`` captures refs while the writer thread does the D2H; the
serve batcher owns the compiled forward and the rng carry. Functions
are annotated ``# ray-tpu: thread=<owner>``; a call from a function
owned by thread A to one owned by thread B is a cross-thread call the
locking was not designed for.

Resolution is same-module: direct ``name(...)`` calls to functions
visible in the caller's scope chain and ``self.method(...)`` calls
within the same class. Unannotated functions are never flagged —
annotate both ends to give the rule teeth on a new surface.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu.analysis.engine import Finding, FuncInfo, ModuleModel
from ray_tpu.analysis.rules._common import class_methods, own_nodes

RULE_ID = "RTA006"


def _resolve(
    model: ModuleModel, caller: FuncInfo, call: ast.Call
) -> Optional[FuncInfo]:
    func = call.func
    if (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id == "self"
    ):
        cls = model.enclosing_class_name(caller.node)
        return class_methods(model, cls).get(func.attr)
    if isinstance(func, ast.Name):
        # nearest visible def by simple name: walk the caller's scope
        # chain outward, ending at module level
        scopes: List[Optional[FuncInfo]] = []
        probe = caller.parent
        while probe is not None:
            scopes.append(probe)
            probe = probe.parent
        scopes.append(None)
        for scope in scopes:
            for fi in model.funcs:
                if fi.parent is scope and fi.node.name == func.id:
                    return fi
    return None


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    for fi in model.funcs:
        if fi.thread is None:
            continue
        for node in own_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolve(model, fi, node)
            if (
                callee is None
                or callee.thread is None
                or callee.thread == fi.thread
            ):
                continue
            f = model.finding(
                RULE_ID,
                node,
                f"`{fi.qualname}` (thread={fi.thread}) calls "
                f"`{callee.qualname}` (thread={callee.thread}) — "
                "cross-thread call into a surface its owner thread "
                "was not designed to share; queue a request or move "
                "the work to the owning thread",
            )
            if f:
                findings.append(f)
    return findings
