"""RTA008 — lock-order discipline across the call graph.

The control plane is full of small per-object locks — the fleet
controller's monitor/driver split, the router's queue condition, the
admission counter, the metrics registry — and calls that cross
objects WHILE HOLDING one of them (``reconcile`` probes a request
manager's in-flight count under the fleet lock). Two threads taking
two locks in opposite orders is the textbook deadlock, and nothing
but reviewer memory tracked the global order until now.

The rule discovers lock objects (attributes or module globals
assigned from ``threading.Lock/RLock/Condition``), collects every
``with <lock>:`` acquisition, and computes ordered pairs
``(outer, inner)``:

- ``with A: ... with B:`` lexically nested in one function;
- ``with A: ... f()`` where ``f`` may (transitively, over the
  whole-program call graph) acquire ``B``.

Any two locks observed in BOTH orders is a finding, reported at the
lexically later inner-acquisition site and naming both witnesses.
Locks are keyed ``Class._name`` / ``module._NAME``, so the rule
reasons about lock OBJECTS, not variable spellings.

Approximations (documented, deliberate): ``.acquire()`` call pairs
are not ordered (the repo idiom is ``with``), and ``Condition.wait``
releasing its lock mid-block is ignored — a pair involving a
condition's wait window can be suppressed with
``# ray-tpu: allow[RTA008] <why>`` at either site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis.engine import Finding, FuncInfo, dotted_name
from ray_tpu.analysis.rules._common import call_name

RULE_ID = "RTA008"

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    parts = call_name(node).split(".")
    return parts[-1] in _LOCK_CTORS and (
        len(parts) == 1 or parts[0] == "threading"
    )


class _Locks:
    """Known lock objects across the program, keyed stably."""

    def __init__(self, program):
        self.program = program
        self.attr_locks: Set[Tuple[str, str]] = set()  # (Class, attr)
        self.global_locks: Set[Tuple[str, str]] = set()  # (mod, name)
        for m in program.modules:
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Assign):
                    continue
                if not _is_lock_ctor(node.value):
                    continue
                cls = m.enclosing_class_name(node)
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and cls is not None
                    ):
                        self.attr_locks.add((cls, tgt.attr))
                    elif (
                        isinstance(tgt, ast.Name)
                        and m.enclosing(node) is None
                    ):
                        self.global_locks.add(
                            (m.module_name, tgt.id)
                        )

    def key_for(
        self, fi: FuncInfo, expr: ast.AST
    ) -> Optional[str]:
        """Stable key of the lock ``expr`` acquires in ``fi``'s
        context, or None when it isn't a known lock."""
        name = dotted_name(expr)
        if not name:
            return None
        m = fi.module
        parts = name.split(".")
        if parts[0] == "self" and len(parts) == 2 and m is not None:
            ci = self.program.class_of(fi)
            cls = ci.name if ci is not None else None
            probe = ci
            depth = 0
            while cls is not None and depth < 8:
                if (cls, parts[1]) in self.attr_locks:
                    return f"{cls}.{parts[1]}"
                # inherited lock attribute
                nxt = None
                if probe is not None and probe.bases:
                    nxt = self.program._resolve_class_name(
                        probe.module, probe.bases[0]
                    )
                probe = nxt if nxt is not probe else None
                cls = probe.name if probe is not None else None
                depth += 1
            return None
        if len(parts) == 1 and m is not None:
            if (m.module_name, parts[0]) in self.global_locks:
                return f"{m.module_name}.{parts[0]}"
        return None


def _acquisitions(
    locks: _Locks, fi: FuncInfo
) -> List[Tuple[str, ast.With]]:
    out: List[Tuple[str, ast.With]] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(fi.node))
    while stack:
        node = stack.pop()
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
        ):
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                key = locks.key_for(fi, item.context_expr)
                if key is not None:
                    out.append((key, node))
        stack.extend(ast.iter_child_nodes(node))
    return out


def check_program(program) -> List[Finding]:
    locks = _Locks(program)

    # per-function: direct acquisitions, and (lock, node) held around
    # each sub-statement
    direct: Dict[FuncInfo, List[Tuple[str, ast.With]]] = {}
    for m in program.modules:
        for fi in m.funcs:
            acq = _acquisitions(locks, fi)
            if acq:
                direct[fi] = acq

    # transitive acquire sets over the call graph
    acq_star: Dict[FuncInfo, Set[str]] = {
        fi: {k for k, _ in acq} for fi, acq in direct.items()
    }
    all_funcs = [
        fi for m in program.modules for fi in m.funcs
    ]
    for fi in all_funcs:
        acq_star.setdefault(fi, set())
    changed = True
    while changed:
        changed = False
        for fi in all_funcs:
            cur = acq_star[fi]
            before = len(cur)
            for g in program.edges.get(fi, ()):
                cur |= acq_star.get(g, set())
            if len(cur) != before:
                changed = True

    # ordered pairs with witness sites: (outer, inner) ->
    # (module, node, holder qualname, detail)
    pairs: Dict[Tuple[str, str], Tuple] = {}

    def note(outer: str, inner: str, m, node, holder: str, why: str):
        if outer == inner:
            return
        pairs.setdefault((outer, inner), (m, node, holder, why))

    for fi, acq in direct.items():
        m = fi.module
        for outer_key, with_node in acq:
            # everything INSIDE this with block
            inner_stack: List[ast.AST] = []
            for stmt in with_node.body:
                inner_stack.append(stmt)
            while inner_stack:
                node = inner_stack.pop()
                if isinstance(
                    node,
                    (
                        ast.FunctionDef,
                        ast.AsyncFunctionDef,
                        ast.ClassDef,
                    ),
                ):
                    continue
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        inner_key = locks.key_for(
                            fi, item.context_expr
                        )
                        if inner_key is not None:
                            note(
                                outer_key,
                                inner_key,
                                m,
                                node,
                                fi.qualname,
                                f"`with {inner_key}` nested inside "
                                f"`with {outer_key}`",
                            )
                if isinstance(node, ast.Call):
                    # skip methods ON the held lock itself
                    # (cv.wait/notify inside `with cv` is the idiom)
                    callee = program.resolve_call(fi, node)
                    if callee is not None:
                        for inner_key in acq_star.get(callee, ()):
                            note(
                                outer_key,
                                inner_key,
                                m,
                                node,
                                fi.qualname,
                                f"call to `{callee.qualname}` (which "
                                f"may acquire {inner_key}) while "
                                f"holding {outer_key}",
                            )
                inner_stack.extend(ast.iter_child_nodes(node))

    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    for (a, b), (m1, n1, q1, why1) in sorted(
        pairs.items(),
        key=lambda kv: (
            kv[1][0].relpath,
            getattr(kv[1][1], "lineno", 0),
        ),
    ):
        if (b, a) not in pairs:
            continue
        if (b, a) in seen or (a, b) in seen:
            continue
        seen.add((a, b))
        m2, n2, q2, why2 = pairs[(b, a)]
        # report at the lexically later witness so the finding sits
        # on the code most recently introduced
        first = (m1.relpath, getattr(n1, "lineno", 0))
        second = (m2.relpath, getattr(n2, "lineno", 0))
        if second >= first:
            m, node, why_here, why_other, other = (
                m2, n2, why2, why1,
                f"{m1.relpath}:{getattr(n1, 'lineno', 0)} "
                f"[{q1}]",
            )
        else:
            m, node, why_here, why_other, other = (
                m1, n1, why1, why2,
                f"{m2.relpath}:{getattr(n2, 'lineno', 0)} "
                f"[{q2}]",
            )
        f = m.finding(
            RULE_ID,
            node,
            f"lock-order inversion between {a} and {b}: here "
            f"{why_here}; the OPPOSITE order ({why_other}) is taken "
            f"at {other} — two threads interleaving these deadlock; "
            "pick one global order or drop the inner acquisition",
        )
        if f:
            findings.append(f)
    return findings
