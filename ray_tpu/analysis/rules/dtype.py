"""RTA003 — weak-type promotion in f64 scopes.

The PR-11 Ape-X bug class: the device sum tree's programs build and
run inside ``sharding.f64_scope()``, where a bare Python float
literal (``|td| + 1e-6``) is WEAK-typed — its result dtype follows
jax's canonicalization for the scope the expression happens to trace
in, not the f64 contract of the tree state. The same expression
evaluated host-side (numpy promotes the literal to f64) and
device-side (weak literal keeps the f32 operand's dtype outside the
scope, or traces differently across scopes) produced diverging
max-priority watermarks. The contract: inside an f64 zone every float
literal that touches array values carries an explicit dtype
(``jnp.float64(1e-6)`` / ``np.float64(...)``).

f64 zones are functions annotated ``# ray-tpu: f64`` (the device
sum-tree program bodies), anything nested in one, and statements
inside a ``with f64_scope():`` block. Device contexts outside an f64
zone are NOT flagged — an f32 learner body's ``0.5 * loss`` is
exactly what weak typing is for.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ray_tpu.analysis.engine import Finding, ModuleModel
from ray_tpu.analysis.rules._common import call_name, own_nodes

RULE_ID = "RTA003"

_DTYPE_CTORS = {
    "float64", "float32", "float16", "asarray", "array", "full",
    "full_like", "zeros", "ones", "arange", "linspace",
}
_JNP_ROOTS = {"jnp", "jax"}


def _is_float_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(
        node.value, float
    ):
        return True
    if (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, (ast.USub, ast.UAdd))
        and isinstance(node.operand, ast.Constant)
        and isinstance(node.operand.value, float)
    ):
        return True
    return False


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[int] = set()

    def add(node, msg):
        if id(node) in seen:
            return
        seen.add(id(node))
        f = model.finding(RULE_ID, node, msg)
        if f:
            findings.append(f)

    def scan_nodes(nodes):
        for node in nodes:
            if isinstance(node, ast.BinOp):
                for side in (node.left, node.right):
                    if _is_float_literal(side):
                        add(
                            side,
                            "bare float literal arithmetic in an f64 "
                            "scope — weak-typed literals canonicalize "
                            "per-scope (the PR-11 `|td|+1e-6` "
                            "divergence); wrap with jnp.float64(...) "
                            "or np.float64(...)",
                        )
            elif isinstance(node, ast.Call):
                parts = call_name(node).split(".")
                if (
                    len(parts) >= 2
                    and parts[0] in _JNP_ROOTS
                    and parts[-1] not in _DTYPE_CTORS
                ):
                    for arg in node.args:
                        if _is_float_literal(arg):
                            add(
                                arg,
                                "bare float literal passed to "
                                f"`{'.'.join(parts)}` in an f64 scope "
                                "— give it an explicit dtype "
                                "(jnp.float64(...)) so both planes "
                                "round identically",
                            )

    for fi in model.funcs:
        if fi.f64:
            scan_nodes(own_nodes(fi))
        else:
            # statements lexically inside `with f64_scope():` blocks
            # of a non-f64 function
            scan_nodes(
                n
                for n in own_nodes(fi)
                if hasattr(n, "lineno")
                and model.in_f64_span(n.lineno)
            )
    return findings
