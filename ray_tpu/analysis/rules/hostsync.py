"""RTA005 — blocking host sync in a hot-path span.

The superstep / serve-batcher / learner-thread spans are annotated
``# ray-tpu: hot-path``: one dispatch and ONE counted drain per
superstep is the whole point of those designs (docs/data_plane.md),
so a stray ``jax.device_get`` / ``.block_until_ready()`` / ``.item()``
inside them silently serializes the pipeline on a device round trip
per call. Sanctioned drains live in helper functions annotated
``# ray-tpu: drain-ok`` (``LearnerThread._drain_lazy``,
``flush_deferred_stats``) or carry an inline
``# ray-tpu: allow[RTA005] <why this drain is counted>``.

The rule flags only the sync PRIMITIVES — calling a drain-ok helper
from a hot span is the sanctioned shape and passes by construction.

v2 upgrade (the whole-program dataflow pass): the rule also runs the
device-taint analysis (:meth:`ProgramModel.taint`) over hot spans and
flags **implicit** syncs — ``float()`` / ``int()`` / ``np.asarray``
/ ``np.array`` coercions whose argument derives from a compiled
program's output. Those block exactly like ``.item()`` but never
spell a sync primitive, so the v1 rule was blind to them.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu.analysis.engine import Finding
from ray_tpu.analysis.rules._common import call_name, own_nodes

RULE_ID = "RTA005"

_SYNC_METHODS = {"item", "block_until_ready"}
_SYNC_FUNCS = {"device_get", "block_until_ready"}
_COERCIONS = {"float", "int", "bool"}
_NP_MATERIALIZERS = {"asarray", "array"}


def _check_module(model, program) -> List[Finding]:
    findings: List[Finding] = []

    def add(node, msg):
        f = model.finding(RULE_ID, node, msg)
        if f:
            findings.append(f)

    for fi in model.funcs:
        if not fi.hot or "drain-ok" in fi.directives:
            continue
        taint = program.taint(fi) if program is not None else None
        for node in own_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            last = name.split(".")[-1]
            if last in _SYNC_FUNCS and (
                "." in name or last == "device_get"
            ):
                add(
                    node,
                    f"blocking `{name}` in hot-path span "
                    f"`{fi.qualname}` — route the readback through a "
                    "counted drain helper (ray-tpu: drain-ok) or "
                    "defer it past the dispatch",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS
            ):
                add(
                    node,
                    f"`.{node.func.attr}()` in hot-path span "
                    f"`{fi.qualname}` blocks on a device round trip "
                    "per call — batch it into the span's one counted "
                    "drain",
                )
            elif taint is not None and node.args:
                # implicit sync: host coercion of a device-derived
                # value (the taint pass tracks program outputs
                # through local aliasing)
                parts = name.split(".")
                is_coercion = (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _COERCIONS
                )
                is_np_mat = (
                    len(parts) == 2
                    and parts[0] in ("np", "numpy", "onp")
                    and parts[1] in _NP_MATERIALIZERS
                )
                if (is_coercion or is_np_mat) and taint.is_device(
                    node.args[0]
                ):
                    add(
                        node,
                        f"`{name}(...)` of a device-program result "
                        f"in hot-path span `{fi.qualname}` — an "
                        "implicit D2H sync (same cost as .item()); "
                        "defer the materialization past the "
                        "dispatch or route it through the counted "
                        "drain",
                    )
    return findings


def check_program(program) -> List[Finding]:
    findings: List[Finding] = []
    for model in program.modules:
        if not program.in_scope(model):
            continue
        findings.extend(_check_module(model, program))
    return findings


def check(model) -> List[Finding]:
    """Per-module fallback (no taint) — kept for direct callers."""
    return _check_module(model, None)
