"""RTA001 — use-after-donate.

``sharded_jit(..., donate_argnums=(i,))`` releases the i-th argument's
buffers to the program's outputs (opt-state double-buffering). Reading
the donated tree after the dispatch is undefined: on real accelerator
backends the buffer is already aliased to an output. The contract is
that the donated expression is REASSIGNED (usually by the same
statement unpacking the program's outputs) before anything reads it.

The rule tracks, per module:

- donating program builders: functions whose body constructs a
  ``sharded_jit``/``ShardedFunction`` with ``donate_argnums`` (the
  repo's ``_build_*`` pattern), plus the cross-module builders the
  sharding layer exports (``build_superstep_fn`` donates position 1);
- donating callables: locals/attributes assigned from those builders
  or from a donating ``sharded_jit`` call directly;

and then flags any Load of a donated argument expression after the
donating call, before a Store to it, within the same function (linear
statement order).

v2 upgrade (the dataflow pass): local ALIASES of the donated
expression are tracked too — ``opt = self.opt_state`` before the
donating call makes a later read of ``opt`` a use-after-donate even
though the donated spelling (``self.opt_state``) was reassigned by
the unpack. Aliasing is the exact trap the double-buffering contract
sets: the name points at the donated buffer, not the fresh one.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis.engine import Finding, ModuleModel
from ray_tpu.analysis.rules._common import (
    call_name,
    const_int_tuple,
    expr_key,
    keyword,
    loads_of,
    own_stmts,
    stores_of,
)

RULE_ID = "RTA001"

#: builders defined elsewhere whose return value donates: position map
KNOWN_BUILDERS: Dict[str, Tuple[int, ...]] = {
    "build_superstep_fn": (1,),  # opt_state (sharding/superstep.py)
}


def _donating_jit_call(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """donate positions if ``node`` is a sharded_jit/ShardedFunction/
    jax.jit call with a literal donate_argnums."""
    if not isinstance(node, ast.Call):
        return None
    last = call_name(node).split(".")[-1]
    if last not in ("sharded_jit", "ShardedFunction", "jit"):
        return None
    kw = keyword(node, "donate_argnums")
    if kw is None:
        return None
    return const_int_tuple(kw)


def _module_builders(model: ModuleModel) -> Dict[str, Tuple[int, ...]]:
    """Function (simple) names in this module that build-and-return a
    donating program."""
    out = dict(KNOWN_BUILDERS)
    for fi in model.funcs:
        positions: Set[int] = set()
        returns = False
        for node in ast.walk(fi.node):
            pos = _donating_jit_call(node)
            if pos:
                positions.update(pos)
            if isinstance(node, ast.Return) and node.value is not None:
                returns = True
        if positions and returns:
            out[fi.node.name] = tuple(sorted(positions))
    return out


def _donating_value(
    node: ast.AST, builders: Dict[str, Tuple[int, ...]]
) -> Optional[Tuple[int, ...]]:
    """donate positions if ``node`` evaluates to a donating program:
    a direct donating jit call, or a call to a known builder."""
    direct = _donating_jit_call(node)
    if direct:
        return direct
    if isinstance(node, ast.Call):
        last = call_name(node).split(".")[-1]
        if last in builders:
            return builders[last]
    return None


def _class_attr_programs(
    model: ModuleModel, builders: Dict[str, Tuple[int, ...]]
) -> Dict[Tuple[Optional[str], str], Tuple[int, ...]]:
    """``self.X = <donating program>`` assignments anywhere in a class
    -> {(class, attr): positions}."""
    out: Dict[Tuple[Optional[str], str], Tuple[int, ...]] = {}
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Assign):
            continue
        pos = _donating_value(node.value, builders)
        if not pos:
            continue
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                cls = model.enclosing_class_name(node)
                out[(cls, tgt.attr)] = pos
    return out


def check(model: ModuleModel) -> List[Finding]:
    builders = _module_builders(model)
    attr_programs = _class_attr_programs(model, builders)
    findings: List[Finding] = []

    for fi in model.funcs:
        stmts = own_stmts(fi)
        cls = model.enclosing_class_name(fi.node)
        local_programs: Dict[str, Tuple[int, ...]] = {}
        # local aliasing: `opt = self.opt_state` makes `opt` another
        # name for the same buffers; keyed alias -> aliased key,
        # indexed by the statement that created the alias
        aliases: Dict[str, Tuple[str, int]] = {}
        # (call id, donated position) -> (key, call, label, idx); the
        # flat stmt list nests (an `if` contains its body stmts), so a
        # call is seen once per enclosing stmt — keep the NARROWEST
        # (greatest index) so the use-after window starts at the
        # call's own statement
        donations: Dict[
            Tuple[int, int], Tuple[str, ast.Call, str, int]
        ] = {}

        for idx, stmt in enumerate(stmts):
            # track locals bound to donating programs (chained
            # targets included: fn = self._fns[k] = build(...))
            if isinstance(stmt, ast.Assign):
                pos = _donating_value(stmt.value, builders)
                if pos:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            local_programs[tgt.id] = pos
                # alias creation / invalidation: `a = <key>` aliases;
                # any other store to `a` clears it
                src_key = expr_key(stmt.value)
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        if src_key is not None and not pos:
                            aliases[tgt.id] = (src_key, idx)
                        else:
                            aliases.pop(tgt.id, None)

            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                pos: Optional[Tuple[int, ...]] = None
                label = ""
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in local_programs
                ):
                    pos = local_programs[node.func.id]
                    label = node.func.id
                elif (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and (cls, node.func.attr) in attr_programs
                ):
                    pos = attr_programs[(cls, node.func.attr)]
                    label = f"self.{node.func.attr}"
                if not pos:
                    continue
                for p in pos:
                    if p >= len(node.args):
                        continue
                    key = expr_key(node.args[p])
                    if key is None:
                        continue
                    donations[(id(node), p)] = (key, node, label, idx)

        for key, call, label, idx in donations.values():
            # the donated key plus every live local alias of it
            # created BEFORE the donating statement
            watched = {key} | {
                a
                for a, (k, aidx) in aliases.items()
                if k == key and aidx < idx
            }
            # the donating statement itself may reassign the donated
            # expr (tuple-unpack of the program outputs): that closes
            # that key's window immediately
            if key in stores_of(stmts[idx]):
                watched.discard(key)
            if not watched:
                continue
            for later in stmts[idx + 1 :]:
                hit = next(
                    (
                        n
                        for k, n in loads_of(later)
                        if k in watched
                    ),
                    None,
                )
                if hit is not None:
                    hit_key = expr_key(hit)
                    via = (
                        ""
                        if hit_key == key
                        else f" (via local alias of `{key}`)"
                    )
                    f = model.finding(
                        RULE_ID,
                        hit,
                        f"`{hit_key}` read after being donated to "
                        f"`{label}`{via} (donate_argnums position — "
                        "the buffer is aliased to the program's "
                        "outputs after dispatch); reassign before "
                        "reading",
                    )
                    if f:
                        findings.append(f)
                    break
                watched -= stores_of(later)
                if not watched:
                    break
    return findings
