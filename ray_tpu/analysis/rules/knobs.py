"""RTA012 — AlgorithmConfig knob reachability and documentation.

``AlgorithmConfig`` is 160+ attributes grown over 14 PRs, consumed
dict-style (``config.get("sample_prefetch")``) far from where they
are declared. Two failure modes accumulate silently: a knob nothing
reads (the setting is a no-op — users tune it and nothing happens),
and a knob the code reads but no doc names (undiscoverable except by
source-diving). Both are drift between the three surfaces — config
module, consuming code, docs/API.md — that nothing reconciled until
now.

For every ``self.<name> = ...`` in the scanned ``AlgorithmConfig``
class body (``__init__``; private ``_names`` excluded):

- **unread knob**: the name appears nowhere outside the defining
  module — neither as a string literal (``config["name"]`` /
  ``.get("name")``) nor as an attribute access — finding at the
  declaration. Fix: wire it, delete it, or mark the deliberate
  API-parity stubs with ``# ray-tpu: allow[RTA012] <why>``;
- **undocumented knob**: the name IS read by code but does not
  appear in ``docs/API.md`` (the config-knob index) — finding at the
  declaration. Fix: add it to the index.

Fixture scans bring their own ``AlgorithmConfig`` class; a scan with
no such class (or no ``docs/API.md`` under the root for the doc arm)
is silent.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

from ray_tpu.analysis.engine import Finding, ModuleModel

RULE_ID = "RTA012"

_CONFIG_CLASS = "AlgorithmConfig"


def _knobs(
    ci,
) -> List[Tuple[str, ast.AST]]:
    """(name, node) for every ``self.<name> =`` in the class's
    ``__init__`` (first binding wins)."""
    init = ci.methods.get("__init__")
    if init is None:
        return []
    seen: Set[str] = set()
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(init.node):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for tgt in targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and not tgt.attr.startswith("_")
                and tgt.attr not in seen
            ):
                seen.add(tgt.attr)
                out.append((tgt.attr, tgt))
    return out


def _reads(program, defining: ModuleModel) -> Set[str]:
    """Every identifier-ish token READ outside the defining module:
    string literals and attribute names (loads only)."""
    out: Set[str] = set()
    for m in program.modules:
        if m is defining:
            continue
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                v = node.value
                if v and len(v) < 80 and v.isidentifier():
                    out.add(v)
            elif isinstance(node, ast.Attribute) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                out.add(node.attr)
    return out


def check_program(program) -> List[Finding]:
    config_classes = [
        ci
        for ci in program.classes.values()
        if ci.name == _CONFIG_CLASS
    ]
    if not config_classes:
        return []
    api_doc = ""
    try:
        with open(
            os.path.join(program.root, "docs", "API.md"),
            encoding="utf-8",
        ) as f:
            api_doc = f.read()
    except OSError:
        pass

    findings: List[Finding] = []
    read_cache: Dict[ModuleModel, Set[str]] = {}
    for ci in config_classes:
        m = ci.module
        knobs = _knobs(ci)
        if not knobs:
            continue
        reads = read_cache.get(m)
        if reads is None:
            reads = _reads(program, m)
            read_cache[m] = reads
        for name, node in knobs:
            if name not in reads:
                f = m.finding(
                    RULE_ID,
                    node,
                    f"config knob `{name}` is never read outside "
                    "the config module — a silent no-op setting; "
                    "wire it into the consuming code, delete it, or "
                    "mark a deliberate API-parity stub with "
                    "allow[RTA012]",
                )
                if f:
                    findings.append(f)
            elif api_doc and name not in api_doc:
                f = m.finding(
                    RULE_ID,
                    node,
                    f"config knob `{name}` is consumed by code but "
                    "absent from docs/API.md — add it to the "
                    "config-knob index so the surface stays "
                    "discoverable",
                )
                if f:
                    findings.append(f)
    return findings
