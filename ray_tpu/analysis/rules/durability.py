"""RTA009 — durability discipline for checkpoint-grade writes.

The crash-safety story (docs/resilience.md) rests on ONE write shape:
same-directory temp file → flush → ``os.fsync`` → ``os.replace`` →
directory fsync. Before this rule, eight modules hand-rolled some
prefix of that chain — several skipped the fsync (a host crash could
publish a rename pointing at unwritten blocks) and most skipped the
directory fsync (the rename itself lives in the directory inode).
The shared helper is :func:`ray_tpu.util.atomic_io.atomic_write`,
annotated ``# ray-tpu: atomic-writer``; everything else routes
through it.

Three checks:

- **hand-rolled rename**: ``os.replace``/``os.rename`` in a function
  NOT annotated ``atomic-writer`` is a finding — route the write
  through the helper;
- **helper validity**: inside an ``atomic-writer`` function the
  ``os.replace`` must be preceded (same function, statement order)
  by an ``os.fsync`` — the rename must not be reorderable ahead of
  the data blocks — and followed (or preceded, for pre-staged dirs)
  by a directory fsync (``fsync_dir``/``_fsync_dir`` call or a
  second ``os.fsync``);
- **raw checkpoint open**: ``open(path, "w"/"wb"/"a")`` where the
  path expression names a checkpoint artifact (``checkpoint`` /
  ``ckpt`` / ``snapshot`` — and, since the fenced-leadership PR,
  ``lease``: the coordinator's lease-term records are what keep a
  zombie ex-leader fenced across a KV restart) in an identifier or
  literal outside an atomic-writer function is a finding — a
  truncate-then-write crash window on the exact files the recovery
  layer trusts.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu.analysis.engine import Finding, FuncInfo, ModuleModel
from ray_tpu.analysis.rules._common import call_name, own_stmts

RULE_ID = "RTA009"

# "lease" covers the fenced-leadership term records (fleet/kv.py):
# a torn lease-term file un-fences a zombie coordinator on restart
_CKPT_TOKENS = ("checkpoint", "ckpt", "snapshot", "lease")
_DIR_FSYNC_NAMES = {"fsync_dir", "_fsync_dir"}


def _is_rename(call: ast.Call) -> bool:
    return call_name(call) in ("os.replace", "os.rename")


def _is_fsync(call: ast.Call) -> bool:
    return call_name(call) == "os.fsync"


def _is_dir_fsync(call: ast.Call) -> bool:
    return call_name(call).split(".")[-1] in _DIR_FSYNC_NAMES


def _mentions_checkpoint(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(
            node.value, str
        ):
            low = node.value.lower()
            if any(t in low for t in _CKPT_TOKENS):
                return True
        if isinstance(node, ast.Name):
            low = node.id.lower()
            if any(t in low for t in _CKPT_TOKENS):
                return True
        if isinstance(node, ast.Attribute):
            low = node.attr.lower()
            if any(t in low for t in _CKPT_TOKENS):
                return True
    return False


def _open_mode(call: ast.Call) -> Optional[str]:
    if call_name(call).split(".")[-1] != "open":
        return None
    mode_node: Optional[ast.AST] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    else:
        for kw in call.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
    if isinstance(mode_node, ast.Constant) and isinstance(
        mode_node.value, str
    ):
        return mode_node.value
    return None


def _writer(fi: FuncInfo) -> bool:
    probe: Optional[FuncInfo] = fi
    while probe is not None:
        if "atomic-writer" in probe.directives:
            return True
        probe = probe.parent
    return False


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []

    def add(node, msg):
        f = model.finding(RULE_ID, node, msg)
        if f:
            findings.append(f)

    for fi in model.funcs:
        stmts = own_stmts(fi)
        # own_stmts nests (an `if` contains its body statements), so
        # dedup calls by identity, keeping the NARROWEST (greatest)
        # statement index for the ordering checks
        by_id = {}
        for idx, stmt in enumerate(stmts):
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    by_id[id(node)] = (idx, node)
        calls = sorted(by_id.values(), key=lambda p: p[0])
        if _writer(fi):
            # the sanctioned implementation: validate the chain
            for idx, node in calls:
                if not _is_rename(node):
                    continue
                fsync_before = any(
                    _is_fsync(n) for i, n in calls if i <= idx
                )
                dir_sync = any(
                    _is_dir_fsync(n) or (_is_fsync(n) and i > idx)
                    for i, n in calls
                )
                if not fsync_before:
                    add(
                        node,
                        f"`{call_name(node)}` in atomic-writer "
                        f"`{fi.qualname}` without a preceding "
                        "`os.fsync` — the rename can be reordered "
                        "ahead of the data blocks; fsync the file "
                        "before publishing it",
                    )
                elif not dir_sync:
                    add(
                        node,
                        f"`{call_name(node)}` in atomic-writer "
                        f"`{fi.qualname}` without a directory fsync "
                        "— the rename lives in the directory inode; "
                        "fsync the directory (util.atomic_io."
                        "fsync_dir) after publishing",
                    )
            continue

        for _, node in calls:
            if _is_rename(node):
                add(
                    node,
                    f"hand-rolled `{call_name(node)}` outside the "
                    "atomic-write helper — route the write through "
                    "`ray_tpu.util.atomic_io.atomic_write` (temp + "
                    "fsync + replace + dir fsync) so a crash cannot "
                    "publish a torn or unsynced file",
                )
                continue
            mode = _open_mode(node)
            if (
                mode is not None
                and ("w" in mode or "a" in mode)
                and node.args
                and _mentions_checkpoint(node.args[0])
            ):
                add(
                    node,
                    f"raw `open(..., {mode!r})` on a checkpoint "
                    "artifact — a crash mid-write leaves a truncated "
                    "file where the recovery layer expects a "
                    "complete one; write through "
                    "`util.atomic_io.atomic_write`",
                )
    return findings
