"""RTA007 — blocking call reachable from the event loop.

The ingress plane is a SINGLE asyncio loop owning every socket
(docs/serving.md "the front door"): one blocking call anywhere in a
coroutine — or in any sync helper a coroutine calls — stalls every
connection at once. Nothing enforced that before this rule: the
ingress plane's async handlers called freely into sync code whose
blocking behavior only review memory tracked.

The rule computes the set of functions reachable over the whole-
program call graph from (a) every ``async def`` body and (b) every
function annotated ``# ray-tpu: thread=<owner>`` whose owner name
ends in ``-loop`` (the ingress loop's thread functions), then flags
the blocking primitives inside that set:

- ``time.sleep`` (``asyncio.sleep`` is the async shape);
- ``Future.result()`` / ``ray.get`` — blocking harvests
  (``await asyncio.wrap_future(fut)`` is the async shape);
- ``jax.device_get`` / ``.block_until_ready()`` — a device round
  trip on the loop stalls every open socket for its duration;
- blocking ``queue.get/put`` (receiver named like a queue, without
  ``block=False``; ``get_nowait``/``put_nowait`` pass);
- sync socket ops (``recv/recv_into/accept/connect/sendall`` on a
  receiver named like a socket);
- ``Event.wait()`` / ``Thread.join()`` — unbounded host blocking
  (``is_set()`` probes pass).

Traversal stops at other ``async def``s (calling one without await
just builds a coroutine) and skips callables passed as ARGUMENTS to
``run_in_executor`` / ``to_thread`` / pool ``submit`` — handing
blocking work to an executor is the sanctioned shape.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from ray_tpu.analysis.engine import Finding, FuncInfo
from ray_tpu.analysis.rules._common import call_name, keyword, own_nodes

RULE_ID = "RTA007"

_LOOP_OWNER_SUFFIX = "-loop"

_QUEUE_NAME_HINTS = ("queue", "_q", "inq", "outq")
_SOCKET_NAME_HINTS = ("sock", "conn")
_BLOCKING_METHODS_ANY = {"result", "block_until_ready"}
_SOCKET_METHODS = {"recv", "recv_into", "accept", "connect", "sendall"}
_WAITY_METHODS = {"wait", "join"}


def _receiver_key(call: ast.Call) -> str:
    func = call.func
    if isinstance(func, ast.Attribute):
        from ray_tpu.analysis.engine import dotted_name

        return (dotted_name(func.value) or "").lower()
    return ""


def _looks_like_queue(recv: str) -> bool:
    leaf = recv.split(".")[-1]
    return any(h in leaf for h in _QUEUE_NAME_HINTS) or leaf == "q"


def _looks_like_socket(recv: str) -> bool:
    leaf = recv.split(".")[-1]
    return any(h in leaf for h in _SOCKET_NAME_HINTS)


def _looks_like_sync_obj(recv: str) -> bool:
    leaf = recv.split(".")[-1]
    return any(
        h in leaf
        for h in ("event", "thread", "ready", "stop", "done", "idle",
                  "wake")
    )


def _blocking_reason(call: ast.Call) -> str:
    """Why this call blocks the loop, or '' when it does not."""
    name = call_name(call)
    parts = name.split(".")
    last = parts[-1]
    if name in ("time.sleep",):
        return "`time.sleep` suspends the whole loop (use `await asyncio.sleep`)"
    if last == "get" and len(parts) >= 2 and parts[0] == "ray":
        return "`ray.get` blocks the loop on a remote result"
    if last == "device_get" and len(parts) >= 2:
        return "`jax.device_get` blocks the loop on a device round trip"
    if not isinstance(call.func, ast.Attribute):
        return ""
    attr = call.func.attr
    recv = _receiver_key(call)
    if attr in _BLOCKING_METHODS_ANY:
        if attr == "result":
            return (
                "`.result()` blocks the loop on a future "
                "(await `asyncio.wrap_future(...)` instead)"
            )
        return "`.block_until_ready()` blocks the loop on the device"
    if attr in ("get", "put") and _looks_like_queue(recv):
        blk = keyword(call, "block")
        if isinstance(blk, ast.Constant) and blk.value is False:
            return ""
        return (
            f"blocking `{recv}.{attr}()` parks the loop on a thread "
            "queue (use the _nowait variant or an executor)"
        )
    if attr in _SOCKET_METHODS and _looks_like_socket(recv):
        return (
            f"sync socket op `{recv}.{attr}()` on the loop thread "
            "(use the asyncio stream APIs)"
        )
    if attr in _WAITY_METHODS and _looks_like_sync_obj(recv):
        return (
            f"`{recv}.{attr}()` blocks the loop on host "
            "synchronization"
        )
    return ""


_EXECUTOR_HANDOFF = {"run_in_executor", "to_thread", "submit"}


def check_program(program) -> List[Finding]:
    roots: List[FuncInfo] = []
    for m in program.modules:
        for fi in m.funcs:
            if fi.is_async or (
                fi.thread is not None
                and fi.thread.endswith(_LOOP_OWNER_SUFFIX)
                and fi.is_async
            ):
                roots.append(fi)
            elif fi.thread is not None and fi.thread.endswith(
                _LOOP_OWNER_SUFFIX
            ):
                # sync functions owned by the loop thread outside the
                # loop runner itself (the runner blocks by design in
                # run_until_complete)
                if not any(
                    call_name(n).endswith("run_until_complete")
                    or call_name(n).endswith("run_forever")
                    for n in own_nodes(fi)
                    if isinstance(n, ast.Call)
                ):
                    roots.append(fi)

    # traversal never enters another async def FROM a call edge: the
    # call builds a coroutine, the loop runs it — blocking inside it
    # is caught because every async def is itself a root
    async_defs = [
        fi
        for m in program.modules
        for fi in m.funcs
        if fi.is_async
    ]
    parents: Dict[FuncInfo, FuncInfo] = {}
    reach: Dict[FuncInfo, FuncInfo] = {}
    for root in roots:
        sub = program.reachable_from(
            [root], stop=[a for a in async_defs if a is not root]
        )
        for fi, par in sub.items():
            if fi not in reach:
                reach[fi] = root
                parents[fi] = par

    findings: List[Finding] = []
    for fi, root in reach.items():
        model = fi.module
        if model is None:
            continue
        for node in own_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            # skip callables handed to an executor: the args are the
            # sanctioned blocking shape, and the executor call itself
            # does not block
            last = call_name(node).split(".")[-1]
            if last in _EXECUTOR_HANDOFF:
                continue
            reason = _blocking_reason(node)
            if not reason:
                continue
            via = (
                ""
                if fi is root
                else f" (reachable from `{root.qualname}` via the "
                f"call graph)"
            )
            f = model.finding(
                RULE_ID,
                node,
                f"{reason} — in `{fi.qualname}`, which runs on the "
                f"event loop{via}; hand blocking work to an executor "
                "or use the async shape",
            )
            if f:
                findings.append(f)
    return findings
