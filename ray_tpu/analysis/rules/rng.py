"""RTA004 — RNG discipline.

Two contracts:

- **No global-stream numpy randomness in library code.** Every random
  draw flows through an explicitly seeded generator object
  (``np.random.default_rng(seed)`` / ``RandomState``) — the bit-exact
  generator invariant the replay planes depend on. Direct
  ``np.random.seed`` / ``np.random.randint`` / ... calls mutate or
  read interpreter-global state that any import can perturb.

- **Split-order discipline for PRNG keys.** A jax PRNG key is a
  VALUE: feeding the same key to two samplers silently correlates
  them, and the per-update host split order is the bitwise-parity
  contract for every lane (superstep = K individual calls). A key
  variable must be re-derived (``jax.random.split`` / ``fold_in``)
  between consecutive sampler consumptions.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu.analysis.engine import Finding, ModuleModel
from ray_tpu.analysis.rules._common import call_name, expr_key

RULE_ID = "RTA004"

_NP_ROOTS = {"np", "numpy", "np_", "onp"}
#: explicit-state constructors/types — the sanctioned surface
_NP_RANDOM_ALLOWED = {
    "default_rng", "Generator", "RandomState", "PCG64", "Philox",
    "SFC64", "MT19937", "SeedSequence", "BitGenerator",
}
#: jax.random.* that derive keys rather than consuming them
_KEY_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "clone",
                 "key_data", "wrap_key_data"}


def _jax_random_attr(call: ast.Call) -> str:
    parts = call_name(call).split(".")
    if len(parts) >= 2 and parts[-2] == "random" and parts[0] in (
        "jax",
        "jrandom",
    ):
        return parts[-1]
    if parts[0] in ("jrandom", "jax_random") and len(parts) == 2:
        return parts[-1]
    return ""


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []

    def add(node, msg):
        f = model.finding(RULE_ID, node, msg)
        if f:
            findings.append(f)

    # (a) global numpy stream anywhere in library code
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        parts = call_name(node).split(".")
        if (
            len(parts) >= 3
            and parts[0] in _NP_ROOTS
            and parts[1] == "random"
            and parts[2] not in _NP_RANDOM_ALLOWED
        ):
            add(
                node,
                f"direct `np.random.{parts[2]}` uses the "
                "interpreter-global stream — thread a seeded "
                "`np.random.default_rng` generator instead "
                "(bit-exact generator contract)",
            )

    # (b) per-function key double-consumption: a block-structured
    # linear scan. Branches fork the consumption state (an if/else
    # where each arm consumes the key once is legal); loops scan
    # their body once with a forked state.
    def scan_calls(stmt, consumed):
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            attr = _jax_random_attr(node)
            if not attr or attr in _KEY_DERIVERS or not node.args:
                continue
            # the key is a sampler's FIRST positional argument
            key = expr_key(node.args[0])
            if key is None:
                continue
            if key in consumed:
                add(
                    node,
                    f"PRNG key `{key}` consumed by a second "
                    f"sampler (`jax.random.{attr}`) without an "
                    "interleaving split/fold_in — correlated "
                    "streams break the split-order parity contract",
                )
            else:
                consumed[key] = node

    def pop_stores(stmt, consumed):
        for node in ast.walk(stmt):
            if isinstance(
                node, (ast.Name, ast.Attribute)
            ) and isinstance(
                getattr(node, "ctx", None), (ast.Store, ast.Del)
            ):
                key = expr_key(node)
                if key:
                    consumed.pop(key, None)

    def scan_block(stmts, consumed):
        for stmt in stmts:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue  # scanned as its own function
            if isinstance(stmt, ast.If):
                for branch in (stmt.body, stmt.orelse):
                    scan_block(branch, dict(consumed))
                pop_stores(stmt, consumed)
            elif isinstance(
                stmt, (ast.For, ast.AsyncFor, ast.While)
            ):
                scan_block(stmt.body, dict(consumed))
                scan_block(stmt.orelse, dict(consumed))
                pop_stores(stmt, consumed)
            elif isinstance(stmt, ast.Try):
                scan_block(stmt.body, dict(consumed))
                for h in stmt.handlers:
                    scan_block(h.body, dict(consumed))
                scan_block(stmt.orelse, dict(consumed))
                scan_block(stmt.finalbody, consumed)
                pop_stores(stmt, consumed)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                scan_block(stmt.body, consumed)
            else:
                scan_calls(stmt, consumed)
                pop_stores(stmt, consumed)

    for fi in model.funcs:
        scan_block(fi.node.body, {})
    return findings
