"""RTA010 — metric/span catalog consistency against the docs.

The Prometheus catalog is 60+ hand-maintained families and the span
map another two dozen names; dashboards, the report CLI, and the
roll-up all key on them by STRING. A renamed family or an
undocumented span silently orphans a dashboard panel — the exact
drift class the "one place, so docs/tests/dashboards can't drift"
comment in ``telemetry/metrics.py`` hoped convention would prevent.
This rule makes the doc the enforced source of truth:

- every metric family name constructed in code — a string literal
  matching ``ray_tpu_[a-z0-9_]+`` assigned at module level or passed
  to an instrument constructor — must appear in
  ``docs/observability.md``;
- for instrument declarations with an explicit ``tag_keys=(...)``,
  every tag key must appear on the doc line(s) that mention the
  family (the catalog table row documents the label set — a tag the
  row doesn't name is an undocumented cardinality axis);
- every literal span name opened via ``start_span("...")`` or
  ``context_span(ctx, "...")`` must be documented: the full name
  appears in the doc, a documented ``prefix:*`` glob covers it, or it
  starts with a stage prefix of ``telemetry/rollup.py``'s
  ``STAGE_PREFIXES`` map (when that module is in the scan). Dynamic
  names (``"jit:" + label``) are checked by their constant prefix;
- fleet-scoped families (``ray_tpu_fleet_*`` / ``ray_tpu_kv_*``) must
  additionally name the ``host`` label in their catalog row: every
  fleet-plane series is host-attributed — either tagged at the source
  or ``host=``-injected by the fleetview aggregator — and a row that
  doesn't say so misdocuments the merged exposition's cardinality.

The doc is read once per scan; with no ``docs/observability.md``
under the scan root the rule is silent (fixture scans anchor
``root`` at the repo, so fixtures exercise it against the real doc).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis.engine import Finding, ModuleModel
from ray_tpu.analysis.rules._common import call_name, keyword

RULE_ID = "RTA010"

_FAMILY_RE = re.compile(r"^ray_tpu_[a-z0-9_]+$")
# fleet-plane families: their doc rows must name the `host` label
_HOST_SCOPED_RE = re.compile(r"^ray_tpu_(fleet|kv)_")
_INSTRUMENT_CTORS = {
    "Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
    "timer_histogram", "get_metric",
}
# opener -> index of the span-name argument (context_span takes the
# propagated context first, the name second)
_SPAN_OPENERS = {"start_span": 0, "context_span": 1}


def _doc(program) -> Optional[str]:
    path = os.path.join(program.root, "docs", "observability.md")
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def _doc_globs(doc: str) -> List[str]:
    """Documented ``prefix:*`` globs (e.g. ``recovery:*``)."""
    return re.findall(r"([a-z_]+:)\*", doc)


def _rollup_prefixes(program) -> List[str]:
    m = program.by_name.get("ray_tpu.telemetry.rollup")
    if m is None:
        return []
    out: List[str] = []
    for node in ast.walk(m.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "STAGE_PREFIXES"
            for t in node.targets
        ):
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and isinstance(
                sub.value, str
            ):
                out.append(sub.value)
    return out


def _literal_prefix(node: ast.AST) -> Optional[Tuple[str, bool]]:
    """(text, is_full) for a span-name argument: a constant string is
    full; the constant LEFT side of ``"p:" + x`` or an f-string's
    leading literal is a prefix."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _literal_prefix(node.left)
        if left is not None:
            return left[0], False
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(
            first.value, str
        ):
            return first.value, False
    return None


def check_program(program) -> List[Finding]:
    doc = _doc(program)
    if doc is None:
        return []
    doc_lines = doc.splitlines()
    globs = _doc_globs(doc)
    stage_prefixes = _rollup_prefixes(program)
    findings: List[Finding] = []

    def add(model: ModuleModel, node, msg):
        f = model.finding(RULE_ID, node, msg)
        if f:
            findings.append(f)

    _row_cache: Dict[str, List[str]] = {}

    def family_rows(name: str) -> List[str]:
        rows = _row_cache.get(name)
        if rows is None:
            rows = [ln for ln in doc_lines if name in ln]
            _row_cache[name] = rows
        return rows

    # metric family names: module-level constants + ctor args ---------
    for m in program.modules:
        if m.module_name.startswith("ray_tpu.analysis"):
            continue
        if not program.in_scope(m):
            continue
        # module-level NAME = "ray_tpu_..."
        consts: Dict[str, Tuple[str, ast.AST]] = {}
        for node in m.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant
            ):
                val = node.value.value
                if isinstance(val, str) and _FAMILY_RE.match(val):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            consts[tgt.id] = (val, node.value)
        for name, (val, node) in consts.items():
            rows = family_rows(val)
            if not rows:
                add(
                    m,
                    node,
                    f"metric family `{val}` is not documented in "
                    "docs/observability.md — add a catalog row (the "
                    "doc is the enforced source of truth for "
                    "dashboards)",
                )
            elif _HOST_SCOPED_RE.match(val) and "host" not in " ".join(
                rows
            ):
                add(
                    m,
                    node,
                    f"fleet-plane family `{val}` has a catalog row "
                    "that never mentions the `host` label — every "
                    "ray_tpu_fleet_*/ray_tpu_kv_* series is "
                    "host-attributed in the merged exposition "
                    "(tagged at the source or injected by the "
                    "fleetview aggregator); document it",
                )

        # instrument constructions: name + tag_keys
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            last = call_name(node).split(".")[-1]
            if last not in _INSTRUMENT_CTORS or not node.args:
                continue
            arg = node.args[0]
            family: Optional[str] = None
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ):
                if _FAMILY_RE.match(arg.value):
                    family = arg.value
            elif isinstance(arg, ast.Name) and arg.id in consts:
                family = consts[arg.id][0]
            if family is None:
                continue
            rows = family_rows(family)
            if not rows:
                add(
                    m,
                    node,
                    f"metric family `{family}` is not documented in "
                    "docs/observability.md — add a catalog row",
                )
                continue
            if (
                isinstance(arg, ast.Constant)
                and _HOST_SCOPED_RE.match(family)
                and "host" not in " ".join(rows)
            ):
                # literal ctor names never went through the
                # module-const check above — same host-label contract
                add(
                    m,
                    node,
                    f"fleet-plane family `{family}` has a catalog "
                    "row that never mentions the `host` label — "
                    "document it (merged-exposition cardinality)",
                )
            tags = keyword(node, "tag_keys")
            if tags is None:
                continue
            tag_names = [
                n.value
                for n in ast.walk(tags)
                if isinstance(n, ast.Constant)
                and isinstance(n.value, str)
            ]
            row_text = " ".join(rows)
            for t in tag_names:
                if t not in row_text:
                    add(
                        m,
                        node,
                        f"metric family `{family}` declares tag "
                        f"`{t}` but its docs/observability.md row "
                        "does not name it — document the full label "
                        "set (undocumented tags are unbudgeted "
                        "cardinality)",
                    )

    # span names -------------------------------------------------------
    def span_covered(text: str, is_full: bool) -> bool:
        if is_full and text in doc:
            return True
        if not is_full and text and text in doc:
            return True
        for g in globs:
            if text.startswith(g):
                return True
        for p in stage_prefixes:
            if text.startswith(p) or (not is_full and p.startswith(text)):
                return True
        return False

    for m in program.modules:
        if m.module_name.startswith("ray_tpu.analysis"):
            continue
        if not program.in_scope(m):
            continue
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            idx = _SPAN_OPENERS.get(call_name(node).split(".")[-1])
            if idx is None or len(node.args) <= idx:
                continue
            lit = _literal_prefix(node.args[idx])
            if lit is None:
                continue
            text, is_full = lit
            if span_covered(text, is_full):
                continue
            kind = "span" if is_full else "span prefix"
            add(
                m,
                node.args[0],
                f"{kind} `{text}` is not in the documented span map "
                "(docs/observability.md) nor covered by a rollup "
                "stage prefix — document it (or fold it into an "
                "existing stage) so timelines and the report CLI "
                "stay navigable",
            )
    return findings
