"""Shared AST helpers for the analyzer rules."""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from ray_tpu.analysis.engine import FuncInfo, ModuleModel, dotted_name


def own_nodes(fi: FuncInfo) -> Iterable[ast.AST]:
    """Every node inside ``fi`` excluding nested def/class subtrees
    (those are classified and scanned on their own). Lambdas count as
    part of the enclosing function."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fi.node))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def own_stmts(fi: FuncInfo) -> List[ast.stmt]:
    """Ordered statement list of ``fi``'s body, recursing into
    control-flow blocks but not nested defs. Linear program order is
    approximated by source position."""
    out = [n for n in own_nodes(fi) if isinstance(n, ast.stmt)]
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return out


def expr_key(node: ast.AST) -> Optional[str]:
    """Stable key for a Name or a Name-rooted attribute chain
    (``opt_state`` / ``self.opt_state``); None for anything else."""
    return dotted_name(node)


def stores_of(stmt: ast.stmt) -> Set[str]:
    """expr_keys written by ``stmt`` (assign/augassign/for targets,
    ``with ... as`` bindings, deletions)."""
    out: Set[str] = set()
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
            getattr(node, "ctx", None), (ast.Store, ast.Del)
        ):
            key = expr_key(node)
            if key:
                out.add(key)
    return out


def loads_of(stmt: ast.stmt) -> List[Tuple[str, ast.AST]]:
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(stmt):
        if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            key = expr_key(node)
            if key:
                out.append((key, node))
    return out


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func) or ""


def const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Evaluate a literal int / tuple-of-ints AST node (the only
    shapes ``donate_argnums`` takes)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(
                el.value, int
            ):
                vals.append(el.value)
            else:
                return None
        return tuple(vals)
    return None


def keyword(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def class_methods(
    model: ModuleModel, class_name: Optional[str]
) -> dict:
    """Map method name -> FuncInfo for the named class."""
    if class_name is None:
        return {}
    out = {}
    for fi in model.funcs:
        if model.enclosing_class_name(fi.node) == class_name:
            out.setdefault(fi.node.name, fi)
    return out
