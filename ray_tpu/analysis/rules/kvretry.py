"""RTA013 — unretried KV transport on a control-plane path.

The fleet KV client's ONE sanctioned path to the wire is the retried
wrapper (``KVClient._roundtrip``, annotated ``# ray-tpu:
kv-retry-wrapper``): transient connect/timeout failures back off and
re-attempt under a bounded per-op deadline, so a control-plane thread
(HostAgent, HeartbeatReporter, HostExporter) survives a KV restart
window instead of hanging or dying on the first refused connect
(docs/fleet.md "Failure model & leadership"). Three ways to defeat
that contract, each flagged:

- calling the raw single-attempt ``_roundtrip_once`` from a function
  not itself annotated ``kv-retry-wrapper``;
- opening a raw socket (``socket.create_connection`` /
  ``socket.socket``) inside a ``thread=``-annotated control-plane
  function that is not a sanctioned wrapper;
- constructing ``KVClient(..., retry=False)`` — a client whose every
  op is one unretried attempt.

Deliberate raw transport (tests proving retry behavior, one-shot
probes where failure is the datum) carries
``# ray-tpu: allow[RTA013] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu.analysis.engine import Finding, ModuleModel
from ray_tpu.analysis.rules._common import own_nodes

RULE_ID = "RTA013"

_RAW_SOCKET_ATTRS = {"create_connection", "socket"}


def _is_raw_socket_call(node: ast.Call) -> bool:
    func = node.func
    return (
        isinstance(func, ast.Attribute)
        and func.attr in _RAW_SOCKET_ATTRS
        and isinstance(func.value, ast.Name)
        and func.value.id == "socket"
    )


def _is_kvclient_ctor(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == "KVClient"
    if isinstance(func, ast.Attribute):
        return func.attr == "KVClient"
    return False


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []
    for fi in model.funcs:
        wrapper = "kv-retry-wrapper" in fi.directives
        for node in own_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "_roundtrip_once"
                and not wrapper
            ):
                f = model.finding(
                    RULE_ID,
                    node,
                    f"`{fi.qualname}` calls the raw single-attempt "
                    "`_roundtrip_once` outside a `# ray-tpu: "
                    "kv-retry-wrapper` function — one refused connect "
                    "during a KV restart kills this path; go through "
                    "the retried `_roundtrip`",
                )
                if f:
                    findings.append(f)
            elif (
                fi.thread is not None
                and not wrapper
                and _is_raw_socket_call(node)
            ):
                f = model.finding(
                    RULE_ID,
                    node,
                    f"`{fi.qualname}` (thread={fi.thread}) opens a raw "
                    "socket on a control-plane thread — route KV ops "
                    "through the retried KVClient transport (or "
                    "annotate the sanctioned wrapper `# ray-tpu: "
                    "kv-retry-wrapper`)",
                )
                if f:
                    findings.append(f)
    # module-level and in-function KVClient(..., retry=False)
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call) or not _is_kvclient_ctor(
            node
        ):
            continue
        for kw in node.keywords:
            if (
                kw.arg == "retry"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
            ):
                f = model.finding(
                    RULE_ID,
                    node,
                    "`KVClient(..., retry=False)` builds an unretried "
                    "transport: every op is a single attempt that dies "
                    "on a KV restart window — drop the kwarg (default "
                    "schedule) or justify with an allow",
                )
                if f:
                    findings.append(f)
    return findings
