"""RTA011 — host-RNG call-order determinism under device-derived
conditionals.

Every lane pins fixed-seed bit-parity tests (superstep ≡ K calls,
device tree ≡ host tree, router coalescing ≡ sequential), and they
all rest on ONE invariant: the host generator's draw ORDER is a pure
function of the seed and the step count. A draw sitting under an
``if`` whose predicate derives from a DEVICE value breaks that in
the worst way — the stream stays plausible, parity only diverges on
the runs where the device value crossed the threshold (XLA and numpy
rounding the predicate differently is enough). This is the dynamic
cousin of the PR-11 ``|td|+1e-6`` bug: not a value divergence but a
draw-count divergence.

The rule runs the whole-program taint pass
(:meth:`ProgramModel.taint`: compiled-program results,
``jax.device_get``, ``.item()``/``.tolist()``, propagated through
local aliasing) and flags any host-generator draw — a method call
like ``integers``/``random``/``normal``/``uniform``/``choice``/
``permutation``/``shuffle``/``standard_normal`` on a receiver named
like a generator (``rng``/``_rng``/``gen``/``generator``/
``random_state``) — lexically inside an ``if``/``while``/ternary
whose test is device-tainted.

Draws under CONFIG conditionals are fine (same branch every run);
draws that consume a device value as an ARGUMENT are fine (the order
is unchanged); a deliberately adaptive draw documents itself with
``# ray-tpu: allow[RTA011] <why the parity contract does not apply>``.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu.analysis.engine import Finding, dotted_name
from ray_tpu.analysis.rules._common import call_name

RULE_ID = "RTA011"

_DRAW_METHODS = {
    "integers", "random", "normal", "uniform", "choice",
    "permutation", "shuffle", "standard_normal", "exponential",
    "randint", "rand", "randn", "sample",
}
_GEN_HINTS = ("rng", "generator", "random_state", "nprandom")


def _is_host_draw(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in _DRAW_METHODS:
        return False
    recv = (dotted_name(call.func.value) or "").lower()
    leaf = recv.split(".")[-1]
    return (
        any(h in leaf for h in _GEN_HINTS)
        or leaf in ("gen", "g")
    )


def check_program(program) -> List[Finding]:
    findings: List[Finding] = []
    for m in program.modules:
        if not program.in_scope(m):
            continue
        for fi in m.funcs:
            # device bodies use jax PRNG keys, not host generators;
            # the contract here is host-side
            if fi.device:
                continue
            taint = None  # computed lazily: most functions have no
            # conditional draws at all
            stack: List[ast.AST] = list(
                ast.iter_child_nodes(fi.node)
            )
            while stack:
                node = stack.pop()
                if isinstance(
                    node,
                    (
                        ast.FunctionDef,
                        ast.AsyncFunctionDef,
                        ast.ClassDef,
                    ),
                ):
                    continue
                tests = []
                bodies = []
                if isinstance(node, (ast.If, ast.While)):
                    tests = [node.test]
                    bodies = [node.body, node.orelse]
                elif isinstance(node, ast.IfExp):
                    tests = [node.test]
                    bodies = [[node.body], [node.orelse]]
                if tests:
                    draws = [
                        sub
                        for blk in bodies
                        for stmt in blk
                        for sub in ast.walk(stmt)
                        if isinstance(sub, ast.Call)
                        and _is_host_draw(sub)
                    ]
                    if draws:
                        if taint is None:
                            taint = program.taint(fi)
                        if any(
                            taint.is_tainted(t) for t in tests
                        ):
                            for d in draws:
                                f = m.finding(
                                    RULE_ID,
                                    d,
                                    f"host-generator draw "
                                    f"`{call_name(d)}` under a "
                                    "conditional whose predicate "
                                    "derives from a device value — "
                                    "the draw COUNT now depends on "
                                    "device rounding, breaking the "
                                    "fixed-seed bit-parity contract; "
                                    "draw unconditionally and select "
                                    "the result, or move the "
                                    "decision to a host-deterministic "
                                    "signal",
                                )
                                if f:
                                    findings.append(f)
                stack.extend(ast.iter_child_nodes(node))
    return findings
