"""Whole-program model: symbol table, call graph, global facts, taint.

PR 12's engine classified each module in isolation — its device/thread
fixed point stopped at module boundaries, so the exact cross-module
chains the ingress and resilience planes run (router batcher → server
submit, streamer thread → atomic writer) were invisible. This module
is the v2 upgrade: it consumes every :class:`ModuleModel` of a scan
and builds

- a **repo-wide symbol table** — per-module imports, module-level
  functions, classes with their methods, base classes, and attribute
  types (``self.router = CoalescingRouter(...)`` /
  ``self._streamer: CheckpointStreamer`` / annotated ``__init__``
  params bound straight to ``self``);
- a **call graph** — every resolvable call edge: lexical-scope names,
  ``self.method()`` (through base classes), ``self.attr.method()``
  through the attribute types above, ``mod.func()`` /
  ``mod.Class(...)`` / ``Class.method()`` through the import table,
  and locals whose class is inferable from an annotation or a
  constructor assignment. Unresolvable calls (stdlib, jax, dynamic
  dispatch) simply contribute no edge — the analysis stays sound for
  what it claims and silent about the rest;
- **global fixed points** — device-context, f64-zone, and
  thread-owner facts propagated along the call edges to a repo-wide
  fixed point (``FuncInfo.device`` / ``.f64`` / ``.owners``), so
  RTA002/RTA003 see trace-time helpers in other modules and
  RTA007/RTA008 know which threads can execute a function;
- a **light intraprocedural dataflow pass** — per-function local
  aliasing plus "value derived from a device array" taint
  (:meth:`ProgramModel.taint`): sources are compiled-program results,
  ``jax.device_get``, ``.item()`` / ``.tolist()``; taint flows
  through assignments, subscripts, arithmetic, and simple coercions.
  RTA005 upgrades onto it (device-derived coercions in hot spans),
  RTA011 uses it for conditional host-RNG draws, and RTA001's
  alias tracking rides the same machinery.

The model is pure ``ast`` — building it never imports jax — and costs
one extra walk over the already-parsed trees.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ray_tpu.analysis.engine import (
    FuncInfo,
    ModuleModel,
    dotted_name,
)

__all__ = ["ClassInfo", "ProgramModel", "TaintInfo"]


@dataclass(eq=False)
class ClassInfo:
    name: str
    qualname: str  # module.Class
    module: ModuleModel
    node: ast.ClassDef
    methods: Dict[str, FuncInfo] = field(default_factory=dict)
    bases: List[str] = field(default_factory=list)  # as written
    #: self.<attr> -> ClassInfo (resolved after all classes exist)
    attr_types: Dict[str, "ClassInfo"] = field(default_factory=dict)


class TaintInfo:
    """Per-function device-taint state in two strengths, tracked over
    local expression keys (``x`` / ``self.x`` dotted chains) in
    linearized statement order (a forward approximation: once
    tainted, a key stays tainted until stored clean):

    - ``device``: the value is (or contains) a still-on-device array —
      a compiled program's output that nothing materialized yet.
      Coercing one blocks (RTA005's implicit-sync check).
    - ``derived``: a HOST value computed from device data
      (``jax.device_get`` / ``.item()`` results and anything built
      from them). Reading one is free, but branching on one makes
      host control flow a function of device rounding (RTA011).
    """

    def __init__(
        self,
        device: Set[str],
        derived: Set[str],
        sources: Dict[str, int],
    ):
        self.device = device
        self.derived = derived
        self.sources = sources  # key -> line of the tainting stmt

    def _hits(self, expr: ast.AST, keys: Set[str]) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, (ast.Name, ast.Attribute)):
                key = dotted_name(node)
                if key and key in keys:
                    return True
        return False

    def is_device(self, expr: ast.AST) -> bool:
        """``expr`` reads a still-on-device program output."""
        return self._hits(expr, self.device)

    def is_tainted(self, expr: ast.AST) -> bool:
        """``expr`` depends on device data at all (device OR derived
        keys, or a materializing call inside the expression)."""
        if self._hits(expr, self.device | self.derived):
            return True
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and _is_taint_source(node):
                return True
        return False


# -- taint helpers ----------------------------------------------------

_SYNC_SOURCES = {"item", "tolist"}


def _is_taint_source(call: ast.Call) -> bool:
    name = dotted_name(call.func) or ""
    last = name.split(".")[-1]
    if last == "device_get":
        return True
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _SYNC_SOURCES
    ):
        return True
    return False


def _compiled_value(node: ast.AST) -> bool:
    """Whether ``node`` is a call that builds or IS a compiled device
    program invocation shape: ``sharded_jit(...)`` or the repo's
    ``_build_*_fn`` / ``build_*_fn`` builders."""
    if not isinstance(node, ast.Call):
        return False
    last = (dotted_name(node.func) or "").split(".")[-1]
    return (
        last == "sharded_jit"
        or (last.startswith("_build_") and last.endswith("_fn"))
        or (last.startswith("build_") and last.endswith("_fn"))
    )


class ProgramModel:
    """The whole-program view over one scan's :class:`ModuleModel`s."""

    def __init__(self, modules: Sequence[ModuleModel], root: str):
        self.root = os.path.abspath(root)
        self.modules: List[ModuleModel] = list(modules)
        self.by_name: Dict[str, ModuleModel] = {
            m.module_name: m for m in self.modules
        }
        # module -> {alias: dotted target module}
        self._mod_imports: Dict[ModuleModel, Dict[str, str]] = {}
        # module -> {alias: (target module dotted, symbol name)}
        self._sym_imports: Dict[
            ModuleModel, Dict[str, Tuple[str, str]]
        ] = {}
        # module -> {name: top-level FuncInfo}
        self._mod_funcs: Dict[ModuleModel, Dict[str, FuncInfo]] = {}
        self.classes: Dict[str, ClassInfo] = {}  # module.Class
        self._class_by_simple: Dict[str, List[ClassInfo]] = {}
        self._class_of_method: Dict[FuncInfo, ClassInfo] = {}
        self._local_types_cache: Dict[
            FuncInfo, Dict[str, ClassInfo]
        ] = {}

        self._build_symbols()
        self._build_attr_types()
        # call graph: caller -> [(call node, callee)]
        self.calls: Dict[
            FuncInfo, List[Tuple[ast.Call, FuncInfo]]
        ] = {}
        self.edges: Dict[FuncInfo, Set[FuncInfo]] = {}
        self.redges: Dict[FuncInfo, Set[FuncInfo]] = {}
        self._build_call_graph()
        self._propagate_facts()
        self._taints: Dict[FuncInfo, TaintInfo] = {}
        #: --since scope (repo-relative paths) or None for full
        #: scans; the engine sets it so per-module sweeps inside
        #: program rules can skip out-of-scope modules
        self.affected: Optional[Set[str]] = None

    def in_scope(self, model: ModuleModel) -> bool:
        return self.affected is None or model.relpath in self.affected

    # -- symbol table ----------------------------------------------------

    def _build_symbols(self) -> None:
        for m in self.modules:
            mod_imports: Dict[str, str] = {}
            sym_imports: Dict[str, Tuple[str, str]] = {}
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        name = alias.asname or alias.name.split(".")[0]
                        target = (
                            alias.name
                            if alias.asname
                            else alias.name.split(".")[0]
                        )
                        mod_imports[name] = target
                elif isinstance(node, ast.ImportFrom):
                    if node.level:  # relative: resolve against module
                        # "from . import x" in pkg/mod.py (level 1)
                        # targets pkg; in pkg/__init__.py it targets
                        # pkg itself (the package IS the module name)
                        parts = m.module_name.split(".")
                        drop = node.level - (
                            1 if m.relpath.endswith("__init__.py") else 0
                        )
                        base = parts[: len(parts) - drop] if drop else parts
                        prefix = ".".join(base)
                        target_mod = (
                            f"{prefix}.{node.module}"
                            if node.module
                            else prefix
                        )
                    else:
                        target_mod = node.module or ""
                    for alias in node.names:
                        if alias.name == "*":
                            continue
                        name = alias.asname or alias.name
                        sym_imports[name] = (target_mod, alias.name)
            self._mod_imports[m] = mod_imports
            self._sym_imports[m] = sym_imports

            funcs: Dict[str, FuncInfo] = {}
            for fi in m.funcs:
                if fi.parent is None and "." not in fi.qualname:
                    funcs.setdefault(fi.node.name, fi)
            self._mod_funcs[m] = funcs

            for node in ast.walk(m.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                qual = f"{m.module_name}.{node.name}"
                ci = ClassInfo(
                    name=node.name,
                    qualname=qual,
                    module=m,
                    node=node,
                    bases=[
                        dotted_name(b)
                        for b in node.bases
                        if dotted_name(b)
                    ],
                )
                for fi in m.funcs:
                    if (
                        m.enclosing_class_name(fi.node) == node.name
                        and isinstance(
                            m.parent(fi.node), ast.ClassDef
                        )
                    ):
                        ci.methods.setdefault(fi.node.name, fi)
                        self._class_of_method[fi] = ci
                self.classes[qual] = ci
                self._class_by_simple.setdefault(
                    node.name, []
                ).append(ci)

    def class_of(self, fi: FuncInfo) -> Optional[ClassInfo]:
        return self._class_of_method.get(fi)

    def _resolve_class_name(
        self, module: ModuleModel, name: str
    ) -> Optional[ClassInfo]:
        """A class named ``name`` (dotted allowed) as visible from
        ``module``: local class, imported symbol, or — as a fallback —
        the unique class of that simple name anywhere in the scan."""
        parts = name.split(".")
        simple = parts[-1]
        # local class in the same module
        ci = self.classes.get(f"{module.module_name}.{simple}")
        if ci is not None and len(parts) == 1:
            return ci
        # from X import Class
        sym = self._sym_imports.get(module, {}).get(parts[0])
        if sym is not None:
            tmod, tname = sym
            if len(parts) == 1:
                hit = self.classes.get(f"{tmod}.{tname}")
                if hit is not None:
                    return hit
        # import x.y as m; m.Class
        if len(parts) >= 2:
            alias = self._mod_imports.get(module, {}).get(parts[0])
            if alias is not None:
                hit = self.classes.get(f"{alias}.{simple}")
                if hit is not None:
                    return hit
        if ci is not None:
            return ci
        cands = self._class_by_simple.get(simple, [])
        return cands[0] if len(cands) == 1 else None

    def _build_attr_types(self) -> None:
        for ci in self.classes.values():
            m = ci.module
            # class-level annotations
            for stmt in ci.node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    t = self._ann_class(m, stmt.annotation)
                    if t is not None:
                        ci.attr_types[stmt.target.id] = t
            for meth in ci.methods.values():
                params = self._param_types(m, meth)
                for node in ast.walk(meth.node):
                    if isinstance(node, ast.AnnAssign):
                        tgt = node.target
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            t = self._ann_class(m, node.annotation)
                            if t is not None:
                                ci.attr_types[tgt.attr] = t
                    elif isinstance(node, ast.Assign):
                        t = self._value_class(m, node.value, params)
                        if t is None:
                            continue
                        for tgt in node.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                ci.attr_types[tgt.attr] = t

    def _ann_class(
        self, module: ModuleModel, ann: Optional[ast.AST]
    ) -> Optional[ClassInfo]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(
            ann.value, str
        ):  # string annotation
            return self._resolve_class_name(
                module, ann.value.strip("'\"")
            )
        if isinstance(ann, ast.Subscript):  # Optional[Foo]
            sub = ann.slice
            if isinstance(sub, ast.Tuple):
                return None
            return self._ann_class(module, sub)
        name = dotted_name(ann)
        if name:
            return self._resolve_class_name(module, name)
        return None

    def _param_types(
        self, module: ModuleModel, fi: FuncInfo
    ) -> Dict[str, ClassInfo]:
        out: Dict[str, ClassInfo] = {}
        args = fi.node.args
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            t = self._ann_class(module, a.annotation)
            if t is not None:
                out[a.arg] = t
        return out

    def _value_class(
        self,
        module: ModuleModel,
        value: ast.AST,
        params: Dict[str, ClassInfo],
    ) -> Optional[ClassInfo]:
        """The class an assigned VALUE constructs or forwards:
        ``Foo(...)`` or a bare annotated parameter name."""
        if isinstance(value, ast.Call):
            name = dotted_name(value.func)
            if name and name[:1].isalpha():
                last = name.split(".")[-1]
                if last[:1].isupper():  # constructor convention
                    return self._resolve_class_name(module, name)
            return None
        if isinstance(value, ast.Name):
            return params.get(value.id)
        return None

    # -- call resolution --------------------------------------------------

    def _method_on(
        self, ci: Optional[ClassInfo], name: str, _depth: int = 0
    ) -> Optional[FuncInfo]:
        if ci is None or _depth > 8:
            return None
        hit = ci.methods.get(name)
        if hit is not None:
            return hit
        for base in ci.bases:
            bci = self._resolve_class_name(ci.module, base)
            if bci is ci:
                continue
            hit = self._method_on(bci, name, _depth + 1)
            if hit is not None:
                return hit
        return None

    def _attr_class(
        self, fi: FuncInfo, ci: Optional[ClassInfo], attr: str,
        _depth: int = 0,
    ) -> Optional[ClassInfo]:
        if ci is None or _depth > 8:
            return None
        hit = ci.attr_types.get(attr)
        if hit is not None:
            return hit
        for base in ci.bases:
            bci = self._resolve_class_name(ci.module, base)
            if bci is not ci:
                hit = self._attr_class(fi, bci, attr, _depth + 1)
                if hit is not None:
                    return hit
        return None

    def resolve_call(
        self, caller: FuncInfo, call: ast.Call
    ) -> Optional[FuncInfo]:
        m = caller.module
        if m is None:
            return None
        func = call.func
        name = dotted_name(func)
        if not name:
            return None
        parts = name.split(".")
        ci = self._class_of_method.get(caller)

        if parts[0] in ("self", "cls") and ci is not None:
            if len(parts) == 2:
                return self._method_on(ci, parts[1])
            if len(parts) == 3:
                tci = self._attr_class(caller, ci, parts[1])
                return self._method_on(tci, parts[2])
            return None

        if len(parts) == 1:
            # lexical scope chain (nested defs), then module level
            probe = caller.parent
            while probe is not None:
                for fi in m.funcs:
                    if (
                        fi.parent is probe
                        and fi.node.name == parts[0]
                    ):
                        return fi
                probe = probe.parent
            hit = self._mod_funcs[m].get(parts[0])
            if hit is not None:
                return hit
            # imported function / class constructor
            sym = self._sym_imports.get(m, {}).get(parts[0])
            if sym is not None:
                tmod, tname = sym
                target = self.by_name.get(tmod)
                if target is not None:
                    f = self._mod_funcs.get(target, {}).get(tname)
                    if f is not None:
                        return f
                tci = self.classes.get(f"{tmod}.{tname}")
                if tci is not None:
                    return self._method_on(tci, "__init__")
            # local class constructor
            tci = self.classes.get(f"{m.module_name}.{parts[0]}")
            if tci is not None:
                return self._method_on(tci, "__init__")
            return None

        # Class.method / var.method / mod.func / mod.Class(...)
        head, rest = parts[0], parts[1:]
        # a local whose class is inferable
        tci = self._local_type(caller, head)
        if tci is not None and len(rest) == 1:
            return self._method_on(tci, rest[0])
        # a class symbol visible here
        tci = self._resolve_class_name(m, head)
        if tci is not None and head[:1].isupper():
            if len(rest) == 1:
                return self._method_on(tci, rest[0])
            return None
        # module alias
        target_name = self._mod_imports.get(m, {}).get(head)
        if target_name is not None:
            # longest-prefix module match: mod.sub.func
            for cut in range(len(rest), 0, -1):
                mod_dotted = ".".join([target_name] + rest[: cut - 1])
                target = self.by_name.get(mod_dotted)
                if target is None:
                    continue
                leaf = rest[cut - 1 :]
                if len(leaf) == 1:
                    f = self._mod_funcs.get(target, {}).get(leaf[0])
                    if f is not None:
                        return f
                    tci = self.classes.get(
                        f"{mod_dotted}.{leaf[0]}"
                    )
                    if tci is not None:
                        return self._method_on(tci, "__init__")
                elif len(leaf) == 2:
                    tci = self.classes.get(
                        f"{mod_dotted}.{leaf[0]}"
                    )
                    if tci is not None:
                        return self._method_on(tci, leaf[1])
                break
        return None

    def _local_type(
        self, fi: FuncInfo, name: str
    ) -> Optional[ClassInfo]:
        cache = self._local_types_cache.get(fi)
        if cache is None:
            cache = self._build_local_types(fi)
            self._local_types_cache[fi] = cache
        return cache.get(name)

    def _build_local_types(
        self, fi: FuncInfo
    ) -> Dict[str, ClassInfo]:
        """name -> class for every local whose type is inferable:
        annotated params, ``x = Foo(...)``, ``x: Foo = ...``, and
        ``x = self.attr`` forwarding a typed attribute. One walk per
        function, cached (resolve_call hits this per attribute
        call)."""
        m = fi.module
        if m is None:
            return {}
        out: Dict[str, ClassInfo] = dict(self._param_types(m, fi))
        ci = self._class_of_method.get(fi)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign):
                t = self._value_class(m, node.value, out)
                if t is None and (
                    isinstance(node.value, ast.Attribute)
                    and isinstance(node.value.value, ast.Name)
                    and node.value.value.id == "self"
                ):
                    t = self._attr_class(fi, ci, node.value.attr)
                if t is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.setdefault(tgt.id, t)
            elif isinstance(node, ast.AnnAssign):
                if isinstance(node.target, ast.Name):
                    t = self._ann_class(m, node.annotation)
                    if t is not None:
                        out.setdefault(node.target.id, t)
        return out

    # -- call graph -------------------------------------------------------

    def _own_calls(self, fi: FuncInfo) -> Iterable[ast.Call]:
        stack: List[ast.AST] = list(ast.iter_child_nodes(fi.node))
        while stack:
            node = stack.pop()
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    def _build_call_graph(self) -> None:
        for m in self.modules:
            for fi in m.funcs:
                out: List[Tuple[ast.Call, FuncInfo]] = []
                for call in self._own_calls(fi):
                    callee = self.resolve_call(fi, call)
                    if callee is not None and callee is not fi:
                        out.append((call, callee))
                self.calls[fi] = out
                self.edges[fi] = {c for _, c in out}
                for c in self.edges[fi]:
                    self.redges.setdefault(c, set()).add(fi)

    # -- global fixed points ----------------------------------------------

    def _propagate_facts(self) -> None:
        # seed thread owners from annotations (engine already
        # inherited `thread` lexically)
        for m in self.modules:
            for fi in m.funcs:
                if fi.thread is not None:
                    fi.owners = {fi.thread}

        changed = True
        while changed:
            changed = False
            for fi, callees in self.edges.items():
                for g in callees:
                    # device facts cross module boundaries: whatever
                    # a device context calls executes at trace time
                    if (
                        fi.device
                        and not g.device
                        and "host-fn" not in g.directives
                    ):
                        g.device = True
                        changed = True
                    # f64 zones extend through device call chains
                    if (
                        fi.f64
                        and fi.device
                        and g.device
                        and not g.f64
                    ):
                        g.f64 = True
                        changed = True
                    # thread owners accumulate on unannotated callees
                    if fi.owners and g.thread is None:
                        before = len(g.owners)
                        g.owners |= fi.owners
                        if len(g.owners) != before:
                            changed = True

    # -- reachability -----------------------------------------------------

    def reachable_from(
        self,
        roots: Iterable[FuncInfo],
        *,
        stop: Optional[Sequence[FuncInfo]] = None,
    ) -> Dict[FuncInfo, FuncInfo]:
        """BFS over call edges from ``roots``. Returns
        ``{reached: parent}`` (roots map to themselves) — the parent
        chain reconstructs a witness path for findings."""
        stop_set = set(stop or ())
        out: Dict[FuncInfo, FuncInfo] = {}
        frontier: List[FuncInfo] = []
        for r in roots:
            if r not in out:
                out[r] = r
                frontier.append(r)
        while frontier:
            cur = frontier.pop()
            for g in self.edges.get(cur, ()):
                if g in out or g in stop_set:
                    continue
                out[g] = cur
                frontier.append(g)
        return out

    def witness(
        self, parents: Dict[FuncInfo, FuncInfo], fi: FuncInfo
    ) -> List[str]:
        chain = [fi]
        seen = {fi}
        while parents.get(chain[-1]) not in (None, chain[-1]):
            nxt = parents[chain[-1]]
            if nxt in seen:
                break
            chain.append(nxt)
            seen.add(nxt)
        return [c.qualname for c in reversed(chain)]

    # -- module dependency closure (--since) ------------------------------

    def affected_by(self, changed_rel: Iterable[str]) -> Set[str]:
        """Repo-relative paths whose findings may change when
        ``changed_rel`` files change: the files themselves plus the
        reverse call-graph/import closure over modules."""
        changed = {p.replace(os.sep, "/") for p in changed_rel}
        mod_deps: Dict[ModuleModel, Set[ModuleModel]] = {}
        for m in self.modules:
            deps: Set[ModuleModel] = set()
            for name in self._mod_imports.get(m, {}).values():
                t = self.by_name.get(name)
                if t is not None:
                    deps.add(t)
            for tmod, _ in self._sym_imports.get(m, {}).values():
                t = self.by_name.get(tmod)
                if t is not None:
                    deps.add(t)
            for fi in m.funcs:
                for g in self.edges.get(fi, ()):
                    if g.module is not None and g.module is not m:
                        deps.add(g.module)
            mod_deps[m] = deps
        rev: Dict[ModuleModel, Set[ModuleModel]] = {}
        for m, deps in mod_deps.items():
            for d in deps:
                rev.setdefault(d, set()).add(m)
        seeds = [m for m in self.modules if m.relpath in changed]
        out: Set[ModuleModel] = set(seeds)
        frontier = list(seeds)
        while frontier:
            cur = frontier.pop()
            for dep in rev.get(cur, ()):
                if dep not in out:
                    out.add(dep)
                    frontier.append(dep)
        return {m.relpath for m in out} | changed

    # -- taint ------------------------------------------------------------

    def taint(self, fi: FuncInfo) -> TaintInfo:
        cached = self._taints.get(fi)
        if cached is not None:
            return cached
        info = self._compute_taint(fi)
        self._taints[fi] = info
        return info

    def _compute_taint(self, fi: FuncInfo) -> TaintInfo:
        from ray_tpu.analysis.rules._common import stores_of

        device: Set[str] = set()
        derived: Set[str] = set()
        sources: Dict[str, int] = {}
        # locals bound to compiled programs: calling them yields
        # device arrays
        program_locals: Set[str] = set()
        attr_programs: Set[str] = set()  # self.<attr> program attrs
        ci = self._class_of_method.get(fi)
        if ci is not None:
            for meth in ci.methods.values():
                for node in ast.walk(meth.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    if _compiled_value(node.value):
                        for tgt in node.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(
                                    tgt.value, ast.Name
                                )
                                and tgt.value.id == "self"
                            ):
                                attr_programs.add(tgt.attr)

        def _is_program_call(node: ast.Call) -> bool:
            name = dotted_name(node.func) or ""
            parts = name.split(".")
            if len(parts) == 1 and parts[0] in program_locals:
                return True
            return (
                len(parts) == 2
                and parts[0] == "self"
                and parts[1] in attr_programs
            )

        def classify(expr: Optional[ast.AST]) -> Tuple[bool, bool]:
            """(still_device, host_derived) for a value expression.
            A materializing call (device_get/.item/.tolist) anywhere
            in the expression wins: its RESULT is host data even when
            its argument was a device array."""
            if expr is None:
                return False, False
            materializes = False
            dev = False
            der = False
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    if _is_taint_source(node):
                        materializes = True
                    elif _is_program_call(node):
                        dev = True
                if isinstance(node, (ast.Name, ast.Attribute)):
                    key = dotted_name(node)
                    if key:
                        if key in device:
                            dev = True
                        if key in derived:
                            der = True
            if materializes:
                return False, True
            return dev, der

        def store(stmt: ast.stmt, dev: bool, der: bool) -> None:
            for key in stores_of(stmt):
                if dev:
                    device.add(key)
                    derived.discard(key)
                    sources.setdefault(key, stmt.lineno)
                elif der:
                    derived.add(key)
                    device.discard(key)
                    sources.setdefault(key, stmt.lineno)
                else:
                    device.discard(key)
                    derived.discard(key)

        for stmt in _ordered_stmts(fi):
            if isinstance(stmt, ast.Assign):
                if _compiled_value(stmt.value):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            program_locals.add(tgt.id)
                    continue
                dev, der = classify(stmt.value)
                store(stmt, dev, der)
            elif isinstance(stmt, ast.AugAssign):
                dev, der = classify(stmt.value)
                if dev or der:
                    store(stmt, dev, der)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                dev, der = classify(stmt.iter)
                if dev or der:
                    store(stmt, dev, der)
        return TaintInfo(device, derived, sources)


def _ordered_stmts(fi: FuncInfo) -> List[ast.stmt]:
    out: List[ast.stmt] = []
    stack: List[ast.AST] = list(ast.iter_child_nodes(fi.node))
    while stack:
        node = stack.pop()
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
        ):
            continue
        if isinstance(node, ast.stmt):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return out
