"""Rule engine for the device-contract static analyzer.

Pure ``ast`` + ``tokenize`` — importing this module (and running a
scan) never imports jax, so the pass runs in CI images where jax is
broken or absent and costs AST-parse time only.

The engine's job is classification; the rules in
:mod:`ray_tpu.analysis.rules` consume the classified model:

- **Device contexts.** A function whose body is traced into an XLA
  program must obey the trace contracts (no host numpy on tracers, no
  ``.item()``, no Python-value branching — RTA002/RTA003). The
  classifier marks a function as a device context when it is

  * annotated ``# ray-tpu: device-fn``;
  * referenced in the arguments of a known tracing entry point
    (``sharded_jit``, ``jax.jit``, ``jax.shard_map``, ``jax.lax.scan``
    / ``map`` / ``cond`` / ``switch`` / ``while_loop`` /
    ``fori_loop``, ``jax.vmap``, ``jax.grad`` …) in the same module;
  * defined (at any depth) inside one of the repo's device-program
    builders (``_device_update_fn``, ``_nest_device_fn``,
    ``_build_serve_fn``, ``build_superstep_fn`` … — the entry points
    docs/data_plane.md names); or
  * nested inside another device context.

  ``# ray-tpu: host-fn`` overrides all of the above (for builder
  helpers that run at build time, not trace time).

- **f64 zones** (RTA003): functions annotated ``# ray-tpu: f64`` (the
  device sum-tree program bodies), anything nested in one, and
  statements lexically inside a ``with f64_scope():`` block.

- **Thread owners** (RTA006): ``# ray-tpu: thread=<name>`` on a def.

- **Hot paths** (RTA005): ``# ray-tpu: hot-path`` on a def marks a
  superstep/serve-batcher/learner-thread span where blocking D2H must
  go through the counted drain helpers.

Suppression and grandfathering:

- ``# ray-tpu: allow[RTA003] reason`` on the offending line (or the
  comment line directly above it) suppresses that rule there; on a
  ``def`` header it suppresses the rule for the whole function.
- ``analysis/baseline.json`` grandfathers findings keyed by
  ``(rule, path, symbol)`` — symbol is the enclosing function's
  dotted qualname, so entries survive line drift. Stale entries
  (matching nothing) are reported so the baseline only shrinks.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# annotations

_DIRECTIVE_RE = re.compile(r"#\s*ray-tpu:\s*(.+?)\s*$")
_ALLOW_RE = re.compile(r"allow\[([A-Za-z0-9_,\s]+)\]\s*(.*)$")
_THREAD_RE = re.compile(r"thread=([A-Za-z0-9_\-]+)$")

#: directives a def header understands (besides allow/thread)
_FLAG_DIRECTIVES = {
    "device-fn",
    "host-fn",
    "f64",
    "hot-path",
    "drain-ok",
    # RTA009: the sanctioned atomic-write implementation — the ONE
    # place allowed to hand-roll temp + fsync + os.replace
    "atomic-writer",
    # RTA013: the sanctioned retried KV transport — the ONE place
    # allowed to touch the raw socket / single-attempt roundtrip
    "kv-retry-wrapper",
}

#: the tracing entry points whose function arguments become device
#: contexts. Matched on the LAST attribute of the dotted call name,
#: optionally constrained on earlier parts (``lax.map`` yes,
#: builtin ``map`` no).
_ENTRY_LAST = {
    "sharded_jit": None,
    "shard_map": None,
    "vmap": ("jax",),
    "pmap": ("jax",),
    "grad": ("jax",),
    "value_and_grad": ("jax",),
    "remat": ("jax",),
    "jit": ("jax",),
    "scan": ("lax",),
    "map": ("lax",),
    "cond": ("lax",),
    "switch": ("lax",),
    "while_loop": ("lax",),
    "fori_loop": ("lax",),
    "associative_scan": ("lax",),
}

#: repo builder functions whose nested defs are device-program bodies
#: (the known entry points of docs/data_plane.md / ISSUE 12)
DEVICE_ENTRY_BUILDERS = {
    "_device_update_fn",
    "_nest_device_fn",
    "_build_serve_fn",
    "build_superstep_fn",
    "_build_rollout_superstep",
    "_build_learn_fn",
    "_build_action_fn",
    "_build_update_fn",
    "_td_error_device_fn",
}

#: classes whose ``_build_*`` methods contain device bodies
DEVICE_ENTRY_CLASSES = {"JaxRolloutEngine"}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    symbol: str  # dotted qualname of enclosing function, or <module>
    message: str

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "message": self.message,
        }

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col} {self.rule} "
            f"[{self.symbol}] {self.message}"
        )


@dataclass(eq=False)  # identity semantics: usable as dict/set keys
class FuncInfo:
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    qualname: str
    parent: Optional["FuncInfo"]
    directives: Set[str] = field(default_factory=set)
    allow: Set[str] = field(default_factory=set)  # function-scope allows
    thread: Optional[str] = None
    device: bool = False
    f64: bool = False
    hot: bool = False
    # whole-program facts (ray_tpu.analysis.program): the module this
    # def lives in, and every thread owner whose call chains can reach
    # it (seeded from `thread=` annotations, propagated globally)
    module: Optional["ModuleModel"] = None
    owners: Set[str] = field(default_factory=set)

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_entry_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if not name:
        return False
    parts = name.split(".")
    last = parts[-1]
    need = _ENTRY_LAST.get(last, False)
    if need is False:
        return False
    if need is None:
        return True
    # constrained: one of the required tokens must appear earlier in
    # the chain (jax.vmap, jax.lax.scan, lax.map, …)
    return any(tok in parts[:-1] for tok in need)


class ModuleModel:
    """One parsed module plus everything the rules need: the tree,
    per-node enclosing-function map, device/f64/thread/hot
    classification, and the suppression tables."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        # dotted module name derived from the repo-relative path —
        # the whole-program symbol table's key space
        mod = self.relpath[:-3] if self.relpath.endswith(".py") else (
            self.relpath
        )
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        self.module_name = mod.replace("/", ".")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        # line -> directives, line -> allowed rule ids
        self.line_directives: Dict[int, List[str]] = {}
        self.allow_lines: Dict[int, Set[str]] = {}
        self._collect_comments(source)
        # parent pointers + function table
        self._parents: Dict[ast.AST, ast.AST] = {}
        self.funcs: List[FuncInfo] = []
        self._func_of_def: Dict[ast.AST, FuncInfo] = {}
        self._build_funcs()
        self._attach_annotations()
        self.f64_spans = self._find_f64_spans()
        self._classify()

    # -- comments --------------------------------------------------------

    def _collect_comments(self, source: str) -> None:
        try:
            toks = tokenize.generate_tokens(
                io.StringIO(source).readline
            )
            comments = [
                (t.start[0], t.string)
                for t in toks
                if t.type == tokenize.COMMENT
            ]
        except tokenize.TokenError:
            comments = []
        for line, text in comments:
            m = _DIRECTIVE_RE.search(text)
            if not m:
                continue
            body = m.group(1)
            am = _ALLOW_RE.match(body)
            if am:
                rules = {
                    r.strip().upper()
                    for r in am.group(1).split(",")
                    if r.strip()
                }
                self.allow_lines.setdefault(line, set()).update(rules)
                continue
            # space-separated directives: "thread=driver hot-path"
            self.line_directives.setdefault(line, []).extend(
                body.split()
            )

    def allows_at(self, line: int) -> Set[str]:
        """Rule ids suppressed at ``line``: a trailing comment on the
        line itself, or a standalone comment line directly above (with
        any run of further comment lines above that)."""
        out = set(self.allow_lines.get(line, ()))
        probe = line - 1
        while probe >= 1:
            text = (
                self.lines[probe - 1] if probe <= len(self.lines) else ""
            )
            stripped = text.strip()
            if not stripped.startswith("#"):
                break
            out |= self.allow_lines.get(probe, set())
            probe -= 1
        return out

    # -- function table --------------------------------------------------

    def _build_funcs(self) -> None:
        def visit(node, parent, qual, parent_fn):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    q = (
                        f"{qual}.{child.name}" if qual else child.name
                    )
                    fi = FuncInfo(child, q, parent_fn)
                    self.funcs.append(fi)
                    self._func_of_def[child] = fi
                    visit(child, node, q, fi)
                elif isinstance(child, ast.ClassDef):
                    q = (
                        f"{qual}.{child.name}" if qual else child.name
                    )
                    visit(child, node, q, parent_fn)
                else:
                    visit(child, node, qual, parent_fn)

        visit(self.tree, None, "", None)
        for fi in self.funcs:
            fi.module = self

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def enclosing(self, node: ast.AST) -> Optional[FuncInfo]:
        """The FuncInfo whose body contains ``node`` (the node of a def
        maps to its OWN FuncInfo)."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            fi = self._func_of_def.get(cur)
            if fi is not None:
                return fi
            cur = self._parents.get(cur)
        return None

    def symbol_for(self, node: ast.AST) -> str:
        fi = self.enclosing(node)
        return fi.qualname if fi is not None else "<module>"

    def enclosing_class_name(self, node: ast.AST) -> Optional[str]:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self._parents.get(cur)
        return None

    # -- annotations -----------------------------------------------------

    def _header_lines(self, node) -> Iterable[int]:
        """Lines whose directives attach to this def: the def header
        span, plus the contiguous comment block immediately above the
        def (or its first decorator)."""
        first = node.lineno
        if node.decorator_list:
            first = min(
                first, min(d.lineno for d in node.decorator_list)
            )
        body_start = node.body[0].lineno if node.body else node.lineno
        yield from range(first, body_start + 1)
        probe = first - 1
        while probe >= 1:
            text = (
                self.lines[probe - 1] if probe <= len(self.lines) else ""
            )
            stripped = text.strip()
            if not stripped.startswith("#"):
                break
            yield probe
            probe -= 1

    def _attach_annotations(self) -> None:
        for fi in self.funcs:
            for line in self._header_lines(fi.node):
                for d in self.line_directives.get(line, ()):  # flags
                    tm = _THREAD_RE.match(d)
                    if tm:
                        fi.thread = tm.group(1)
                    elif d in _FLAG_DIRECTIVES:
                        fi.directives.add(d)
                fi.allow |= self.allow_lines.get(line, set())

    # -- f64 zones -------------------------------------------------------

    def _find_f64_spans(self) -> List[Tuple[int, int]]:
        spans = []
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    name = dotted_name(expr.func) or ""
                    if name.split(".")[-1] == "f64_scope":
                        spans.append(
                            (
                                node.lineno,
                                getattr(
                                    node, "end_lineno", node.lineno
                                ),
                            )
                        )
        return spans

    def in_f64_span(self, line: int) -> bool:
        return any(a <= line <= b for a, b in self.f64_spans)

    # -- classification --------------------------------------------------

    def _classify(self) -> None:
        # names referenced in the arguments of tracing entry calls
        traced_names: Set[Tuple[Optional[FuncInfo], str]] = set()
        for node in ast.walk(self.tree):
            if not (
                isinstance(node, ast.Call) and is_entry_call(node)
            ):
                continue
            scope = self.enclosing(node)
            for arg in list(node.args):
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        traced_names.add((scope, sub.id))

        by_scope_name: Dict[Tuple[Optional[FuncInfo], str], FuncInfo] = {}
        for fi in self.funcs:
            by_scope_name[(fi.parent, fi.node.name)] = fi

        def name_marked(fi: FuncInfo) -> bool:
            # a def is traced if ITS name is referenced in an entry
            # call from the same scope chain it is visible in
            scope = fi.parent
            probe: Optional[FuncInfo] = scope
            while True:
                if (probe, fi.node.name) in traced_names:
                    # visibility check: the def found by that (scope,
                    # name) lookup must be this one
                    if by_scope_name.get((scope, fi.node.name)) is fi:
                        return True
                if probe is None:
                    return False
                probe = probe.parent

        for fi in self.funcs:
            if "host-fn" in fi.directives:
                fi.device = False
                continue
            dev = "device-fn" in fi.directives or name_marked(fi)
            if not dev:
                anc = fi.parent
                while anc is not None:
                    in_entry_class = (
                        self.enclosing_class_name(anc.node)
                        in DEVICE_ENTRY_CLASSES
                        and anc.node.name.startswith("_build_")
                    )
                    if (
                        anc.node.name in DEVICE_ENTRY_BUILDERS
                        or in_entry_class
                        or anc.device
                    ):
                        dev = True
                        break
                    anc = anc.parent
            fi.device = dev
        # second pass: nesting inside an (already marked) device fn
        for fi in self.funcs:
            if fi.device or "host-fn" in fi.directives:
                continue
            anc = fi.parent
            while anc is not None:
                if anc.device:
                    fi.device = True
                    break
                anc = anc.parent
        # third pass (fixed point): everything a device context CALLS
        # executes at trace time too — propagate along same-module
        # call edges (`name(...)` in scope, `self.method(...)` in the
        # same class)
        by_class: Dict[Tuple[Optional[str], str], FuncInfo] = {}
        for fi in self.funcs:
            cls = self.enclosing_class_name(fi.node)
            by_class.setdefault((cls, fi.node.name), fi)

        def resolve_call(caller: FuncInfo, call: ast.Call):
            func = call.func
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
            ):
                cls = self.enclosing_class_name(caller.node)
                return by_class.get((cls, func.attr))
            if isinstance(func, ast.Name):
                probe = caller.parent
                while True:
                    hit = by_scope_name.get((probe, func.id))
                    if hit is not None:
                        return hit
                    if probe is None:
                        return None
                    probe = probe.parent
            return None

        changed = True
        while changed:
            changed = False
            for fi in self.funcs:
                if not fi.device:
                    continue
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = resolve_call(fi, node)
                    if (
                        callee is not None
                        and not callee.device
                        and "host-fn" not in callee.directives
                    ):
                        callee.device = True
                        changed = True

        for fi in self.funcs:
            f64 = "f64" in fi.directives or self.in_f64_span(
                fi.node.lineno
            )
            if not f64:
                anc = fi.parent
                while anc is not None:
                    if anc.f64:
                        f64 = True
                        break
                    anc = anc.parent
            fi.f64 = f64
            fi.hot = "hot-path" in fi.directives
            if fi.thread is None and fi.parent is not None:
                fi.thread = fi.parent.thread

    # -- rule support ----------------------------------------------------

    def finding(
        self, rule: str, node: ast.AST, message: str
    ) -> Optional[Finding]:
        """Build a Finding unless an allow annotation suppresses it
        (line-scope or enclosing-function scope)."""
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        if rule in self.allows_at(line):
            return None
        fi = self.enclosing(node)
        while fi is not None:
            if rule in fi.allow:
                return None
            fi = fi.parent
        return Finding(
            rule=rule,
            path=self.relpath,
            line=line,
            col=col,
            symbol=self.symbol_for(node),
            message=message,
        )


# ---------------------------------------------------------------------------
# baseline

def load_baseline(path: str) -> List[Dict]:
    with open(path) as f:
        data = json.load(f)
    return list(data.get("entries", []))


def save_baseline(
    path: str,
    findings: Sequence[Finding],
    *,
    keys: Optional[Sequence[Tuple[str, str, str]]] = None,
) -> None:
    """Write the baseline from ``findings`` (deduped per
    ``(rule, path, symbol)``), or from an explicit ``keys`` list when
    the caller merged scopes itself (the ``--since`` +
    ``--write-baseline`` path)."""
    entries = sorted(
        set(keys) if keys is not None else {f.key for f in findings}
    )
    data = {
        "version": 1,
        "entries": [
            {"rule": r, "path": p, "symbol": s}
            for r, p, s in entries
        ],
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


# ---------------------------------------------------------------------------
# scanning

#: version of the machine-readable report (``--json``); bumped on any
#: field change so CI consumers can pin what they parse
SCHEMA_VERSION = 2


@dataclass
class ScanResult:
    findings: List[Finding]  # unbaselined, unsuppressed
    baselined: List[Finding]
    stale_baseline: List[Dict]
    files: int
    duration_s: float
    parse_errors: List[str] = field(default_factory=list)
    mode: str = "full"  # "full" | "since"
    affected_files: Optional[int] = None  # since-mode scope size
    rules_run: int = 0
    # since-mode scope (repo-relative paths); not serialized — the
    # CLI's --write-baseline merge needs it
    affected_paths: Optional[Set[str]] = None

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> Dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "ok": self.ok,
            "mode": self.mode,
            "files": self.files,
            "affected_files": self.affected_files,
            "rules_run": self.rules_run,
            "duration_s": round(self.duration_s, 3),
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": self.stale_baseline,
            "parse_errors": self.parse_errors,
            "counts": self.counts(),
        }

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d
                for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def scan_paths(
    paths: Sequence[str],
    *,
    root: Optional[str] = None,
    baseline: Optional[Sequence[Dict]] = None,
    rules: Optional[Sequence] = None,
    changed: Optional[Sequence[str]] = None,
) -> ScanResult:
    """Scan ``paths`` (files or directories) with every registered
    rule. ``root`` anchors the repo-relative paths findings and
    baseline entries use (default: cwd).

    Every scan parses ALL of ``paths`` and builds the whole-program
    model (symbol table + call graph + global facts — the parse is
    the cheap part and cross-module facts need the full tree).
    ``changed`` (repo-relative paths, the ``--since`` mode) then
    restricts where RULES run: the changed files plus their reverse
    call-graph/import dependents. Findings, baseline hits, and stale
    detection are all scoped to that affected set.
    """
    from ray_tpu.analysis.program import ProgramModel
    from ray_tpu.analysis.rules import all_rules

    root = os.path.abspath(root or os.getcwd())
    active = list(rules) if rules is not None else all_rules()
    t0 = time.perf_counter()
    models: List[ModuleModel] = []
    files = 0
    errors: List[str] = []
    for path in iter_py_files(paths):
        apath = os.path.abspath(path)
        rel = os.path.relpath(apath, root)
        try:
            with open(apath, encoding="utf-8") as f:
                source = f.read()
            models.append(ModuleModel(apath, rel, source))
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{rel}: {e}")
            continue
        files += 1

    program = ProgramModel(models, root)
    affected: Optional[Set[str]] = None
    if changed is not None:
        affected = program.affected_by(changed)
        # program-level rules consult this to skip out-of-scope
        # modules (their findings are filtered to it anyway; the
        # call-graph facts they read were already computed globally)
        program.affected = affected

    raw: List[Finding] = []
    for rule in active:
        if hasattr(rule, "check_program"):
            raw.extend(rule.check_program(program))
        else:
            for model in models:
                if (
                    affected is not None
                    and model.relpath not in affected
                ):
                    continue
                raw.extend(rule.check(model))
    if affected is not None:
        raw = [f for f in raw if f.path in affected]
    raw.sort(key=lambda f: (f.path, f.line, f.rule))

    base_keys = {
        (e["rule"], e["path"], e["symbol"]) for e in (baseline or ())
    }
    kept, grandfathered = [], []
    hit_keys = set()
    for f in raw:
        if f.key in base_keys:
            grandfathered.append(f)
            hit_keys.add(f.key)
        else:
            kept.append(f)
    stale = [
        e
        for e in (baseline or ())
        if (e["rule"], e["path"], e["symbol"]) not in hit_keys
        and (affected is None or e["path"] in affected)
    ]
    return ScanResult(
        findings=kept,
        baselined=grandfathered,
        stale_baseline=stale,
        files=files,
        duration_s=time.perf_counter() - t0,
        parse_errors=errors,
        mode="full" if changed is None else "since",
        affected_files=None if affected is None else len(affected),
        rules_run=len(active),
        affected_paths=affected,
    )


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")
