"""CLI for the device-contract analyzer.

    python -m ray_tpu.analysis [paths...] [--json] [--rules RTA00X,..]
                               [--baseline PATH|--no-baseline]
                               [--write-baseline] [--root DIR]

Exit status: 0 when every finding is suppressed or baselined, 1 when
unbaselined findings remain, 2 on parse errors. Stale baseline
entries are reported (the baseline should only ever shrink) but do
not fail the run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ray_tpu.analysis.engine import (
    default_baseline_path,
    load_baseline,
    save_baseline,
    scan_paths,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.analysis",
        description="ray_tpu device-contract static analyzer",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=None,
        help="files/directories to scan (default: ray_tpu/)",
    )
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--root",
        default=None,
        help="repo root findings/baseline paths are relative to "
        "(default: cwd)",
    )
    ap.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: ray_tpu/analysis/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, grandfathered or not",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write the current findings as the new baseline",
    )
    ap.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root or os.getcwd())
    paths = args.paths or [os.path.join(root, "ray_tpu")]
    baseline_path = args.baseline or default_baseline_path()
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            baseline = load_baseline(baseline_path)

    rules = None
    if args.rules:
        from ray_tpu.analysis.rules import rules_by_id

        rules = rules_by_id(args.rules.split(","))

    result = scan_paths(
        paths, root=root, baseline=baseline, rules=rules
    )

    if args.write_baseline:
        save_baseline(baseline_path, result.findings)
        print(
            f"wrote {len({f.key for f in result.findings})} entries "
            f"to {baseline_path}"
        )
        return 0

    if args.as_json:
        print(json.dumps(result.to_dict(), indent=1))
    else:
        for f in result.findings:
            print(f.render())
        for e in result.stale_baseline:
            print(
                "stale baseline entry (fixed or moved — remove it): "
                f"{e['rule']} {e['path']} [{e['symbol']}]"
            )
        for err in result.parse_errors:
            print(f"parse error: {err}")
        counts = result.counts()
        by_rule = (
            " ("
            + ", ".join(
                f"{r}={n}" for r, n in sorted(counts.items())
            )
            + ")"
            if counts
            else ""
        )
        print(
            f"{len(result.findings)} unbaselined finding(s){by_rule}, "
            f"{len(result.baselined)} baselined, "
            f"{len(result.stale_baseline)} stale baseline entr(ies) — "
            f"{result.files} files in {result.duration_s:.2f}s"
        )
    if result.parse_errors:
        return 2
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
