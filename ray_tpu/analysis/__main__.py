"""CLI for the device-contract analyzer.

    python -m ray_tpu.analysis [paths...] [--json] [--rules RTA00X,..]
                               [--baseline PATH|--no-baseline]
                               [--write-baseline] [--root DIR]
                               [--since REV]

Exit status: 0 when every finding is suppressed or baselined, 1 when
unbaselined findings remain, 2 on parse errors. Stale baseline
entries are reported (the baseline should only ever shrink) but do
not fail the run — ``--write-baseline`` prunes them automatically.

``--since REV`` is the incremental pre-commit mode: the whole tree is
still parsed (cross-module facts need the full call graph — parsing
is the cheap part), but rules run only over the files git reports
changed since ``REV`` plus their reverse call-graph/import
dependents, and findings/baseline bookkeeping is scoped to that set.
A change under ``docs/`` falls back to a full scan (the catalog
rules read the docs). ``--json`` reports carry ``schema_version``
(``engine.SCHEMA_VERSION``) so CI consumers can pin what they parse.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from ray_tpu.analysis.engine import (
    default_baseline_path,
    load_baseline,
    save_baseline,
    scan_paths,
)


def _git_changed(root: str, rev: str):
    """Repo-relative paths changed since ``rev`` (committed, staged,
    unstaged, and untracked). Returns ``(py_paths, docs_changed)`` or
    None when git is unavailable / the rev is bad."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", rev, "--"],
            cwd=root,
            capture_output=True,
            text=True,
            timeout=30,
        )
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root,
            capture_output=True,
            text=True,
            timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    names = [
        ln.strip()
        for ln in (
            diff.stdout.splitlines()
            + (
                untracked.stdout.splitlines()
                if untracked.returncode == 0
                else []
            )
        )
        if ln.strip()
    ]
    py = [n for n in names if n.endswith(".py")]
    docs_changed = any(
        n.startswith("docs/") and n.endswith(".md") for n in names
    )
    return py, docs_changed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.analysis",
        description="ray_tpu device-contract static analyzer",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=None,
        help="files/directories to scan (default: ray_tpu/)",
    )
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--root",
        default=None,
        help="repo root findings/baseline paths are relative to "
        "(default: cwd)",
    )
    ap.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: ray_tpu/analysis/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, grandfathered or not",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write the current findings as the new baseline, "
        "pruning stale entries automatically (under --since, "
        "out-of-scope entries are kept verbatim)",
    )
    ap.add_argument(
        "--rules",
        default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--since",
        default=None,
        metavar="REV",
        help="incremental mode: run rules only on files changed "
        "since REV plus their reverse call-graph dependents",
    )
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root or os.getcwd())
    paths = args.paths or [os.path.join(root, "ray_tpu")]
    baseline_path = args.baseline or default_baseline_path()
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        if os.path.exists(baseline_path):
            baseline = load_baseline(baseline_path)

    rules = None
    if args.rules:
        from ray_tpu.analysis.rules import rules_by_id

        rules = rules_by_id(args.rules.split(","))

    changed = None
    if args.since:
        got = _git_changed(root, args.since)
        if got is None:
            print(
                f"--since {args.since}: git unavailable or bad rev; "
                "falling back to a full scan",
                file=sys.stderr,
            )
        else:
            py, docs_changed = got
            if not docs_changed:
                changed = py
            # else: the catalog rules (RTA010/RTA012) read docs/*.md
            # — a doc edit can change findings anywhere → full scan

    result = scan_paths(
        paths,
        root=root,
        baseline=baseline,
        rules=rules,
        changed=changed,
    )

    if args.write_baseline:
        new_keys = {f.key for f in result.findings}
        pruned = 0
        keys = set(new_keys)
        if os.path.exists(baseline_path):
            old = load_baseline(baseline_path)
            old_keys = {
                (e["rule"], e["path"], e["symbol"]) for e in old
            }
            if result.affected_paths is not None:
                # incremental: out-of-scope entries were not
                # re-validated — keep them; in-scope entries whose
                # finding is gone are pruned
                out_of_scope = {
                    k
                    for k in old_keys
                    if k[1] not in result.affected_paths
                }
                keys |= out_of_scope
                pruned = len(old_keys - keys)
            else:
                pruned = len(old_keys - new_keys)
        save_baseline(
            baseline_path, result.findings, keys=sorted(keys)
        )
        print(
            f"wrote {len(keys)} entr(ies) to {baseline_path}"
            + (f" ({pruned} stale pruned)" if pruned else "")
        )
        return 0

    if args.as_json:
        print(json.dumps(result.to_dict(), indent=1))
    else:
        for f in result.findings:
            print(f.render())
        for e in result.stale_baseline:
            print(
                "stale baseline entry (fixed or moved — remove it, "
                "or run --write-baseline to prune): "
                f"{e['rule']} {e['path']} [{e['symbol']}]"
            )
        for err in result.parse_errors:
            print(f"parse error: {err}")
        counts = result.counts()
        by_rule = (
            " ("
            + ", ".join(
                f"{r}={n}" for r, n in sorted(counts.items())
            )
            + ")"
            if counts
            else ""
        )
        scope = (
            f" [--since scope: {result.affected_files} files]"
            if result.mode == "since"
            else ""
        )
        print(
            f"{len(result.findings)} unbaselined finding(s){by_rule}, "
            f"{len(result.baselined)} baselined, "
            f"{len(result.stale_baseline)} stale baseline entr(ies) — "
            f"{result.files} files in {result.duration_s:.2f}s"
            f"{scope}"
        )
    if result.parse_errors:
        return 2
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
