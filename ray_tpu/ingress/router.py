"""Cross-replica batch coalescing: the router behind the front door.

PR 9's serving plane batches per replica: each ``BatchedPolicyServer``
coalesces only the requests that happened to reach ITS queue, so at
moderate load N replicas run N under-full buckets where one full
bucket would do. This router merges the streams BEFORE dispatch: all
ingress requests for a deployment land in one queue, the batcher
forms **full power-of-two buckets** out of them, and each bucket goes
to exactly one replica as a single atomic run (``submit_many`` /
``PolicyDeployment.handle_rows``).

Why this is recompile-free by construction: replicas only ever execute
the bucket shapes they warmed (the PR-9 power-of-two contract), and a
router-merged bucket is just more real rows in the same padded shapes
— cross-replica merging changes bucket OCCUPANCY, never bucket SHAPE.

Determinism (docs/serving.md): a replica's server advances its rng
carry once per real request in arrival order, and the router dispatches
buckets to a given replica in formation order from one batcher thread —
so the per-request-key contract survives the extra hop: any router
coalescing of a fixed-seed stream onto one replica is BIT-identical to
sequential ``compute_actions`` on a 1-shard mesh
(tests/test_ingress.py).

Reliability:

- **deadlines** — every request may carry one; expired requests are
  dropped at collection time, BEFORE dispatch, so the mesh never
  computes an answer nobody is waiting for;
- **dead replicas** — a dispatch that dies (actor death, stopped
  server, timeout) marks the replica dead, re-queues the bucket's
  unexpired requests at the FRONT of the queue, and the next
  formation routes them to a survivor;
- **membership** — the router polls the serve controller's
  replica-membership feed (``serve.membership_feed`` →
  ``resilience.discovery.MembershipFeed``) between batches, adopting
  autoscaler scale-ups and dead-replica replacements without a
  listener thread of its own.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.serve.policy_server import TrailingWindow, default_buckets
from ray_tpu.telemetry import metrics as telemetry_metrics
from ray_tpu.util import tracing


class DeadlineExpired(RuntimeError):
    """The request's deadline passed before a replica computed it."""


class NoReplicasAvailable(RuntimeError):
    """Every known replica is dead and membership has no fresh ones."""


class LocalReplica:
    """In-process replica client over a ``BatchedPolicyServer`` (or a
    ``PolicyDeployment`` owning one): the zero-copy path tests, bench,
    and single-process deployments use. ``begin`` enqueues the bucket
    atomically on the caller's thread (preserving per-replica FIFO —
    the determinism anchor); ``finish`` blocks for the results on a
    dispatch-pool thread."""

    # trace contexts flow through to BatchedPolicyServer.submit_many
    # (the serve:batch span joins the request's trace)
    accepts_trace = True

    def __init__(self, server, name: str = "local"):
        # accept a PolicyDeployment transparently
        self.server = getattr(server, "server", server)
        self.name = name
        self.dead = False

    def begin(self, rows: Sequence[Any], explore, trace=None):
        return self.server.submit_many(
            rows, explore=explore, trace=trace
        )

    def finish(self, token, timeout_s: float) -> List[Dict[str, Any]]:
        out = []
        deadline = time.perf_counter() + timeout_s
        for fut in token:
            remaining = max(0.0, deadline - time.perf_counter())
            action, extra = fut.result(remaining)
            out.append(
                {
                    "action": action,
                    "params_version": fut.params_version,
                    "extra": extra,
                }
            )
        return out

    def alive(self) -> bool:
        return (
            not self.dead
            and self.server.error is None
            and not self.server._stop.is_set()
        )

    def queue_wait_p50_s(self) -> Optional[float]:
        # the shared accessor (satellite contract): the SAME window
        # stats() feeds the autoscaler also feeds ingress shedding
        return self.server.queue_wait_window()["p50_s"]


class ActorReplica:
    """Replica client over a serve-core ``_Replica`` actor hosting a
    ``PolicyDeployment`` — the multi-process fleet path. ``begin`` is
    the non-blocking actor submit (ordered per actor), ``finish`` the
    bounded harvest; actor-death errors surface in ``finish`` and mark
    the replica dead."""

    def __init__(self, actor, name: str = "replica"):
        self.actor = actor
        self.name = name
        self.dead = False

    def begin(self, rows: Sequence[Any], explore):
        import numpy as np

        return self.actor.call_method.remote(
            "handle_rows",
            [[np.asarray(r).tolist() for r in rows]],
            {"explore": explore},
        )

    def finish(self, token, timeout_s: float) -> List[Dict[str, Any]]:
        import ray_tpu as ray

        return ray.get(token, timeout=timeout_s)

    def alive(self) -> bool:
        return not self.dead

    def queue_wait_p50_s(self) -> Optional[float]:
        # remote stats are the autoscaler's polling job, not the
        # per-request admission path's — no synchronous actor RTT here
        return None


def _is_actor_handle(member) -> bool:
    # NOT a duck-check: an ActorHandle synthesizes an ActorMethod for
    # ANY attribute name, so hasattr() answers True for everything —
    # classification must be by type
    from ray_tpu.core.api import ActorHandle

    return isinstance(member, ActorHandle)


def wrap_replica(member, index: int = 0):
    """Default membership wrap: serve actors → :class:`ActorReplica`,
    in-process servers/deployments → :class:`LocalReplica`."""
    if _is_actor_handle(member):
        return ActorReplica(member, name=f"replica-{index}")
    return LocalReplica(member, name=f"local-{index}")


def _as_client(member, index: int, wrap) -> Any:
    """Normalize one membership entry into a replica client: actor
    handles and bare servers/deployments go through ``wrap``; objects
    already speaking the client protocol (begin/finish) pass through
    — the type check comes FIRST because actor handles would pass any
    hasattr probe."""
    if _is_actor_handle(member):
        return wrap(member, index)
    if hasattr(member, "begin") and hasattr(member, "finish"):
        return member
    return wrap(member, index)


def _safe_reject(fut: Future, err: BaseException) -> None:
    """Reject a request future, tolerating a client that cancelled it
    first (asyncio ``wait_for`` cancels the wrapped future on its own
    timeout) — an InvalidStateError here must never kill a router
    thread."""
    try:
        fut.set_exception(err)
    except Exception:
        pass


def _safe_resolve(fut: Future, value) -> None:
    try:
        if fut.set_running_or_notify_cancel():
            fut.set_result(value)
    except Exception:
        pass


class _RouterRequest:
    __slots__ = (
        "obs",
        "explore",
        "deadline",
        "future",
        "t_submit",
        "trace",
    )

    def __init__(
        self, obs, explore, deadline, future, t_submit, trace=None
    ):
        self.obs = obs
        self.explore = explore
        self.deadline = deadline
        self.future = future
        self.t_submit = t_submit
        # trace context ({"trace_id", "parent_span_id"}) riding batch
        # formation: the bucket's dispatch span joins the trace of its
        # FIRST request (docs/observability.md "Fleet view")
        self.trace = trace


class CoalescingRouter:
    """Merges ingress requests across replicas into full power-of-two
    buckets before dispatch. Thread layout: callers enqueue from any
    thread; ONE batcher thread forms buckets and begins dispatches
    (per-replica FIFO); a small pool harvests results so slow replicas
    never stall bucket formation."""

    def __init__(
        self,
        name: str,
        replicas: Sequence[Any] = (),
        *,
        membership=None,
        wrap: Optional[Callable[[Any, int], Any]] = None,
        max_batch_size: int = 32,
        buckets: Optional[Sequence[int]] = None,
        batch_wait_timeout_s: float = 0.002,
        default_deadline_s: Optional[float] = None,
        dispatch_timeout_s: float = 60.0,
        dispatch_workers: int = 4,
        stats_window_s: float = 30.0,
        start: bool = True,
    ):
        self.name = name
        self.max_batch_size = int(max_batch_size)
        self.buckets = tuple(
            sorted(set(int(b) for b in buckets))
            if buckets
            else default_buckets(self.max_batch_size)
        )
        self.batch_wait_timeout_s = float(batch_wait_timeout_s)
        self.default_deadline_s = default_deadline_s
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self._membership = membership
        self._wrap = wrap or wrap_replica
        self._members_version = -1
        self._members_lock = threading.Lock()
        self._replicas: List[Any] = [
            _as_client(r, i, self._wrap)
            for i, r in enumerate(replicas)
        ]
        self._rr = 0

        self._queue: "collections.deque[_RouterRequest]" = (
            collections.deque()
        )
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self.error: Optional[BaseException] = None

        self.batches_total = 0
        self.merged_rows_total = 0
        self.expired_total = 0
        self.rerouted_total = 0
        self._wait_window = TrailingWindow(stats_window_s)

        self._pool = ThreadPoolExecutor(
            max_workers=int(dispatch_workers),
            thread_name_prefix=f"router_dispatch_{name}",
        )
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._refresh_membership()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"router_batcher_{self.name}",
        )
        self._thread.start()

    # -- client side -----------------------------------------------------

    def submit(
        self,
        obs,
        explore: Optional[bool] = None,
        deadline_s: Optional[float] = None,
        trace: Optional[Dict[str, Any]] = None,
    ) -> Future:
        """Enqueue one observation; returns a ``concurrent.futures``
        Future resolving to ``{"action", "params_version", ...}`` (or
        raising :class:`DeadlineExpired` / :class:`NoReplicasAvailable`).
        ``deadline_s`` is relative; expired requests are dropped
        before dispatch, never computed. ``trace`` is an optional
        tracing context (``tracing.inject_context()``) the bucket's
        downstream spans stitch under."""
        if self._stop.is_set():
            raise RuntimeError("router is stopped")
        now = time.perf_counter()
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        fut: Future = Future()
        req = _RouterRequest(
            obs,
            explore,
            now + deadline_s if deadline_s is not None else None,
            fut,
            now,
            trace,
        )
        with self._cv:
            self._queue.append(req)
            self._cv.notify_all()
        return fut

    # -- batcher thread --------------------------------------------------

    # ray-tpu: thread=router-batcher
    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    while not self._queue and not self._stop.is_set():
                        self._cv.wait()
                    if self._stop.is_set() and not self._queue:
                        break
                self._refresh_membership()
                batch, expired = self._collect()
                self._drop_expired(expired)
                if batch:
                    self._dispatch(batch)
        except BaseException as e:  # pragma: no cover - defensive
            self.error = e
            with self._cv:
                pending = list(self._queue)
                self._queue.clear()
            for req in pending:
                _safe_reject(req.future, e)

    def _refresh_membership(self) -> None:
        """Adopt the controller's current replica set when its feed
        version moved (scale-up, dead-replica replacement). A
        republished membership only ever contains live actors, so a
        fresh wrap also clears stale dead marks — the same contract
        ``DeploymentHandle``'s listener applies."""
        if self._membership is None:
            return
        try:
            version, members = self._membership.current()
        except Exception:
            return
        # called from the batcher thread AND from health/stats readers
        # (an idle router must still adopt a feed that arrived after
        # construction, or healthz reports it degraded forever and a
        # balancer never sends it its first request); the lock makes
        # the version-gated swap safe from any thread
        with self._members_lock:
            if version == self._members_version:
                return
            self._members_version = version
            if members:
                self._replicas = [
                    _as_client(m, i, self._wrap)
                    for i, m in enumerate(members)
                ]

    # ray-tpu: thread=router-batcher
    def _collect(self):
        """Form one bucket: wait for a full ``max_batch_size`` run (or
        the coalesce timeout after the FIRST request), then drain a
        same-explore FIFO run, splitting out expired requests — they
        are dropped before dispatch instead of computing dead work."""
        with self._cv:
            if not self._queue:
                return [], []
            deadline = (
                self._queue[0].t_submit + self.batch_wait_timeout_s
            )
            while (
                len(self._queue) < self.max_batch_size
                and not self._stop.is_set()
            ):
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            now = time.perf_counter()
            batch: List[_RouterRequest] = []
            expired: List[_RouterRequest] = []
            flag = None
            while self._queue and len(batch) < self.max_batch_size:
                req = self._queue[0]
                if req.deadline is not None and now > req.deadline:
                    expired.append(self._queue.popleft())
                    continue
                if flag is None:
                    flag = req.explore
                elif req.explore != flag:
                    break
                batch.append(self._queue.popleft())
            return batch, expired

    def _drop_expired(self, expired) -> None:
        if not expired:
            return
        self.expired_total += len(expired)
        telemetry_metrics.inc_router_expired(self.name, len(expired))
        for req in expired:
            _safe_reject(
                req.future,
                DeadlineExpired(
                    "request expired before dispatch "
                    f"(waited {time.perf_counter() - req.t_submit:.3f}s)"
                ),
            )

    # ray-tpu: thread=router-batcher
    def _next_replica(self):
        n = len(self._replicas)
        for _ in range(n):
            r = self._replicas[self._rr % n]
            self._rr += 1
            if r.alive():
                return r
        return None

    # ray-tpu: thread=router-batcher
    def _dispatch(self, batch: List[_RouterRequest]) -> None:
        """Begin the bucket on one live replica (on THIS thread, so a
        replica sees buckets in formation order — the determinism
        anchor) and hand the blocking harvest to the pool."""
        replica = self._next_replica()
        if replica is None:
            # one forced membership refresh before giving up: the
            # controller may have replaced the corpses already
            self._members_version = -1
            self._refresh_membership()
            replica = self._next_replica()
        if replica is None:
            err = NoReplicasAvailable(
                f"deployment {self.name!r}: no live replicas"
            )
            for req in batch:
                _safe_reject(req.future, err)
            return
        explore = batch[0].explore
        trace = batch[0].trace
        rows = [req.obs for req in batch]
        t0 = time.perf_counter()
        try:
            # replicas opt into trace pass-through via accepts_trace
            # (LocalReplica does); the bare (rows, explore) protocol
            # stays valid for custom replica clients
            if trace is not None and getattr(
                replica, "accepts_trace", False
            ):
                token = replica.begin(rows, explore, trace=trace)
            else:
                token = replica.begin(rows, explore)
        except Exception:
            replica.dead = True
            self._requeue(batch)
            return
        self.batches_total += 1
        self.merged_rows_total += len(batch)
        telemetry_metrics.observe_router_batch(self.name, len(batch))
        for req in batch:
            self._wait_window.observe(t0 - req.t_submit, t=t0)
        self._pool.submit(self._finish, replica, token, batch)

    def _requeue(self, batch: List[_RouterRequest]) -> None:
        """Put a failed bucket's requests back at the FRONT of the
        queue in their original order (expired ones get filtered by
        the next collection). Called from batcher and dispatch
        threads; the queue lock is the designed sharing point."""
        self.rerouted_total += len(batch)
        telemetry_metrics.inc_router_rerouted(self.name, len(batch))
        with self._cv:
            for req in reversed(batch):
                self._queue.appendleft(req)
            self._cv.notify_all()

    # ray-tpu: thread=router-dispatch
    def _finish(self, replica, token, batch) -> None:
        """Harvest one dispatched bucket on a pool thread. A dead or
        wedged replica routes the bucket back through the queue onto
        a survivor."""
        try:
            # joins the trace of the bucket's first request (the
            # ingress:request span), falling back to a fresh span for
            # untraced submissions
            with tracing.context_span(
                getattr(batch[0], "trace", None),
                "router:dispatch",
                rows=len(batch),
                replica=replica.name,
            ):
                results = replica.finish(
                    token, self.dispatch_timeout_s
                )
            if len(results) != len(batch):
                raise RuntimeError(
                    f"replica returned {len(results)} results for "
                    f"{len(batch)} requests"
                )
        except BaseException:
            replica.dead = True
            self._requeue(batch)
            return
        for req, row in zip(batch, results):
            _safe_resolve(req.future, row)

    # -- introspection / lifecycle ---------------------------------------

    def queue_wait_signal(self) -> Optional[float]:
        """The shedding signal for admission control: the worst p50
        queue wait across this router's window and every local
        replica's ``BatchedPolicyServer.queue_wait_window()`` — the
        SAME accessor the serve autoscaler reads through stats()."""
        waits = [self._wait_window.pct(50)]
        for r in self._replicas:
            try:
                waits.append(r.queue_wait_p50_s())
            except Exception:
                pass
        waits = [w for w in waits if w is not None]
        return max(waits) if waits else None

    def num_replicas(self) -> int:
        self._refresh_membership()
        return len(self._replicas)

    def num_dead(self) -> int:
        return sum(0 if r.alive() else 1 for r in self._replicas)

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            depth = len(self._queue)
        return {
            "name": self.name,
            "queue_depth": depth,
            "replicas": self.num_replicas(),
            "dead_replicas": self.num_dead(),
            "batches_total": self.batches_total,
            "merged_rows_total": self.merged_rows_total,
            "mean_merged_rows": (
                self.merged_rows_total / self.batches_total
                if self.batches_total
                else 0.0
            ),
            "expired_total": self.expired_total,
            "rerouted_total": self.rerouted_total,
            "queue_wait": self._wait_window.snapshot(),
            "buckets": list(self.buckets),
        }

    def stop(self, join_timeout: float = 30.0) -> None:
        self._stop.set()
        with self._cv:
            pending = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        err = RuntimeError("router stopped")
        for req in pending:
            _safe_reject(req.future, err)
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=join_timeout)
        self._pool.shutdown(wait=False)
