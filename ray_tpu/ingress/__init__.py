"""``ray_tpu.ingress``: the internet-scale serving front door.

Three layers between a TCP socket and a mesh forward
(docs/serving.md "the front door"):

- :mod:`~ray_tpu.ingress.http` — the asyncio HTTP/ASGI ingress
  (``POST /v1/policy/<name>/actions``, ``/healthz``, ``/metrics``);
- :mod:`~ray_tpu.ingress.router` — cross-replica batch coalescing
  into full power-of-two buckets with deadlines and dead-replica
  rerouting;
- :mod:`~ray_tpu.ingress.admission` — bounded in-flight budget,
  per-policy quotas + queue-wait shedding (429/503 + Retry-After) so
  overload sheds instead of queueing;
- :mod:`~ray_tpu.ingress.supervisor` — horizontal scale-out: N
  ingress worker PROCESSES accepting on ONE port (SO_REUSEPORT or an
  inherited listener), with crash respawn, forwarded membership,
  whole-bank drain, and one merged ``/metrics`` exposition.

Cold starts skip the compile storm via the AOT executable cache
(:mod:`ray_tpu.sharding.aot`), loaded by
``BatchedPolicyServer.warmup(aot_cache=...)``.
"""

from ray_tpu.ingress.admission import (  # noqa: F401
    AdmissionController,
    AdmissionDecision,
)
from ray_tpu.ingress.http import PolicyIngress  # noqa: F401
from ray_tpu.ingress.router import (  # noqa: F401
    ActorReplica,
    CoalescingRouter,
    DeadlineExpired,
    LocalReplica,
    NoReplicasAvailable,
    wrap_replica,
)
from ray_tpu.ingress.supervisor import (  # noqa: F401
    ForwardedFeed,
    IngressSupervisor,
    WorkerContext,
)

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "PolicyIngress",
    "CoalescingRouter",
    "LocalReplica",
    "ActorReplica",
    "DeadlineExpired",
    "NoReplicasAvailable",
    "wrap_replica",
    "IngressSupervisor",
    "ForwardedFeed",
    "WorkerContext",
]
