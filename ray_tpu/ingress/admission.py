"""Admission control and backpressure at the front door.

An ingress without admission control turns overload into unbounded
queue growth: every request is accepted, queue waits climb, deadlines
pass inside the queue, and the mesh spends its cycles computing
answers whose clients already gave up. This module makes the ingress
shed INSTEAD of queueing (docs/serving.md "the front door"):

- **bounded in-flight budget** — at most ``max_inflight`` admitted
  requests may be unanswered at once; past that the ingress answers
  **429 Too Many Requests** with a ``Retry-After`` hint instead of
  enqueueing;
- **per-policy quotas** — a SHARED controller may cap each policy's
  slice of the in-flight budget (``quotas={"policy": n}`` or
  ``default_quota``), so one hot tenant flooding its route cannot
  exhaust the global budget and starve every other policy on the
  mesh; a request past its policy's share gets **429** with reason
  ``quota`` while other policies keep admitting;
- **queue-wait shedding** — when the trailing-window p50 queue wait
  (``BatchedPolicyServer.queue_wait_window()`` — the SAME shared
  accessor the serve autoscaler targets through ``stats()``, surfaced
  via ``CoalescingRouter.queue_wait_signal``) exceeds
  ``shed_queue_wait_s``, new requests get **503 Service Unavailable**
  + ``Retry-After`` sized to the observed wait, letting the
  autoscaler catch up instead of the queue;
- **dead-on-arrival drops** — a request whose deadline is already
  unmeetable is refused immediately (the router separately drops
  requests that expire while queued, before dispatch).

The wait signal is sampled at most every ``signal_interval_s`` so the
admission decision costs one monotonic read per request, not a stats
aggregation.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.telemetry import metrics as telemetry_metrics


class AdmissionDecision:
    """A refusal: HTTP status, machine-readable reason, Retry-After."""

    __slots__ = ("status", "reason", "retry_after_s")

    def __init__(self, status: int, reason: str, retry_after_s: float):
        self.status = status
        self.reason = reason
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Per-policy (or shared) admission state. ``try_admit`` returns
    None to admit — the caller MUST pair it with ``release()`` (or use
    the :meth:`admit` context manager) — or an
    :class:`AdmissionDecision` describing the shed.

    ``quotas`` maps policy name → that policy's in-flight cap inside
    this controller's global ``max_inflight``; ``default_quota``
    applies to policies without an explicit row. Callers opt in by
    passing ``policy=`` to ``try_admit``/``release`` — the pair must
    name the SAME policy."""

    def __init__(
        self,
        *,
        max_inflight: int = 256,
        shed_queue_wait_s: Optional[float] = None,
        wait_signal: Optional[Callable[[], Optional[float]]] = None,
        signal_interval_s: float = 0.25,
        retry_after_s: float = 1.0,
        quotas: Optional[Dict[str, int]] = None,
        default_quota: Optional[int] = None,
    ):
        self.max_inflight = int(max_inflight)
        self.shed_queue_wait_s = shed_queue_wait_s
        self.wait_signal = wait_signal
        self.signal_interval_s = float(signal_interval_s)
        self.retry_after_s = float(retry_after_s)
        self.quotas: Dict[str, int] = {
            str(k): int(v) for k, v in (quotas or {}).items()
        }
        self.default_quota = (
            int(default_quota) if default_quota is not None else None
        )
        self._lock = threading.Lock()
        self._inflight = 0
        self._policy_inflight: Dict[str, int] = {}
        self._signal_value: Optional[float] = None
        self._signal_t = 0.0
        self.admitted_total = 0
        self.shed_total: Dict[str, int] = {
            "inflight": 0, "quota": 0, "queue_wait": 0, "deadline": 0,
        }

    # -- the decision ----------------------------------------------------

    def _current_wait(self) -> Optional[float]:
        """Cached wait signal: refreshed at most once per
        ``signal_interval_s`` so admission stays O(1) per request."""
        if self.wait_signal is None:
            return None
        now = time.monotonic()
        with self._lock:
            fresh = now - self._signal_t < self.signal_interval_s
            if fresh:
                return self._signal_value
            self._signal_t = now
        try:
            value = self.wait_signal()
        except Exception:
            value = None
        with self._lock:
            self._signal_value = value
        return value

    def _quota_for(self, policy: Optional[str]) -> Optional[int]:
        if policy is None:
            return None
        q = self.quotas.get(policy)
        return q if q is not None else self.default_quota

    def try_admit(
        self,
        deadline_s: Optional[float] = None,
        policy: Optional[str] = None,
    ) -> Optional[AdmissionDecision]:
        """Admit (None) or shed (a decision). ``deadline_s`` is the
        request's RELATIVE deadline; non-positive means it cannot be
        met no matter what — refused without touching the queue.
        ``policy`` enables the per-tenant quota check and MUST be
        echoed to the paired ``release``."""
        if deadline_s is not None and deadline_s <= 0:
            return self._shed("deadline", 504, self.retry_after_s)
        wait = self._current_wait()
        if (
            self.shed_queue_wait_s is not None
            and wait is not None
            and wait > self.shed_queue_wait_s
        ):
            # Retry-After sized to the congestion actually observed:
            # long enough for the autoscaler / the queue to drain
            return self._shed(
                "queue_wait",
                503,
                max(self.retry_after_s, 2.0 * wait),
            )
        quota = self._quota_for(policy)
        with self._lock:
            if self._inflight >= self.max_inflight:
                reason = "inflight"
            elif (
                quota is not None
                and self._policy_inflight.get(policy, 0) >= quota
            ):
                reason = "quota"
            else:
                reason = None
                self._inflight += 1
                self.admitted_total += 1
                inflight = self._inflight
                if policy is not None:
                    self._policy_inflight[policy] = (
                        self._policy_inflight.get(policy, 0) + 1
                    )
                    policy_inflight = self._policy_inflight[policy]
        if reason is not None:
            return self._shed(reason, 429, self.retry_after_s)
        telemetry_metrics.set_ingress_inflight(inflight)
        if policy is not None:
            telemetry_metrics.set_ingress_policy_inflight(
                policy, policy_inflight
            )
        return None

    def _shed(
        self, reason: str, status: int, retry_after_s: float
    ) -> AdmissionDecision:
        with self._lock:
            self.shed_total[reason] = (
                self.shed_total.get(reason, 0) + 1
            )
        telemetry_metrics.inc_ingress_shed(reason)
        return AdmissionDecision(status, reason, retry_after_s)

    def release(self, policy: Optional[str] = None) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            inflight = self._inflight
            if policy is not None:
                self._policy_inflight[policy] = max(
                    0, self._policy_inflight.get(policy, 0) - 1
                )
                policy_inflight = self._policy_inflight[policy]
        telemetry_metrics.set_ingress_inflight(inflight)
        if policy is not None:
            telemetry_metrics.set_ingress_policy_inflight(
                policy, policy_inflight
            )

    class _Admit:
        __slots__ = ("ctrl", "decision", "policy")

        def __init__(self, ctrl, decision, policy=None):
            self.ctrl = ctrl
            self.decision = decision
            self.policy = policy

        @property
        def admitted(self) -> bool:
            return self.decision is None

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            if self.admitted:
                self.ctrl.release(self.policy)
            return False

    def admit(
        self,
        deadline_s: Optional[float] = None,
        policy: Optional[str] = None,
    ) -> "AdmissionController._Admit":
        """``with ctrl.admit(...) as a:`` — ``a.admitted`` says
        whether to proceed; release happens on exit automatically."""
        return self._Admit(
            self, self.try_admit(deadline_s, policy=policy), policy
        )

    # -- introspection ---------------------------------------------------

    def num_inflight(self, policy: Optional[str] = None) -> int:
        with self._lock:
            if policy is not None:
                return self._policy_inflight.get(policy, 0)
            return self._inflight

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "inflight": self._inflight,
                "max_inflight": self.max_inflight,
                "admitted_total": self.admitted_total,
                "shed_total": dict(self.shed_total),
                "shed_queue_wait_s": self.shed_queue_wait_s,
                "last_wait_signal": self._signal_value,
                "quotas": dict(self.quotas),
                "default_quota": self.default_quota,
                "policy_inflight": dict(self._policy_inflight),
            }
