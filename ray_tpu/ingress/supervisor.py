"""Horizontal front door: N ingress worker PROCESSES on ONE port.

A single :class:`~ray_tpu.ingress.http.PolicyIngress` event loop is
the serving plane's aggregate-throughput ceiling — one process parses
every request, runs every admission check, serializes every response.
This module scales the front door OUT (docs/serving.md "Scaling the
front door"): an :class:`IngressSupervisor` runs ``num_workers``
worker processes, each a full ``PolicyIngress`` with its own event
loop and its own :class:`~ray_tpu.ingress.router.CoalescingRouter`
stack, all accepting on the SAME ``host:port``:

- **SO_REUSEPORT** (the default wherever the kernel offers it): every
  worker binds its own listening socket on the shared port and the
  kernel balances incoming connections across the bank;
- **inherited-listener fallback**: the supervisor binds ONE listening
  socket before forking and every worker accepts from it (fd
  inheritance across ``fork`` — the fd-passing path without a unix
  socket ceremony), sharing one accept queue.

The supervisor is the bank's control plane, all over per-worker
duplex pipes:

- **membership forwarding** — the supervisor subscribes to the
  serve-controller membership feed (``serve.membership_feed``) in the
  controller process and forwards ``(version, encoded-members)`` to
  every worker; each worker's router follows a
  :class:`ForwardedFeed`, so autoscaler scale-ups and dead-replica
  replacements reach every process from the ONE controller feed;
- **respawn** — a crashed worker is detected by process liveness and
  replaced; the replacement re-runs ``worker_init`` and is immediately
  re-sent the last-known membership, drain state, and merged metrics
  (``ray_tpu_ingress_workers{state=}`` /
  ``ray_tpu_ingress_worker_respawns_total``);
- **whole-bank drain** — the supervisor probes
  ``resilience.provider_notice`` for its host and broadcasts the
  notice, flipping EVERY worker into the PR-19 healthz-503 +
  connection-close drain at once (``drain()`` does the same on
  demand);
- **merged /metrics** — workers push registry snapshots
  (``telemetry.fleetview.registry_snapshot``) on their heartbeat; the
  supervisor merges them through a
  :class:`~ray_tpu.telemetry.fleetview.FleetAggregator` (counters
  SUM, gauges last-write, histograms bucket-wise, each series labeled
  ``host="ingress-w<i>"``) and broadcasts the merged exposition back,
  where each worker serves it from ``/metrics`` via the fleetview
  render hook — ANY worker's scrape shows the whole bank.

Workers are forked, so ``worker_init`` may be any closure: it runs
INSIDE the worker process with a :class:`WorkerContext` (the worker's
ingress, its index, and ``ctx.membership(name)`` feeds) and mounts
policies — typically restoring a checkpoint into an in-process
replica stack, or wrapping forwarded member descriptors via the
router's ``wrap=``. Serve-core actor handles are NOT forwardable
across processes; encode membership to descriptors your ``wrap`` can
resolve worker-side.
"""

from __future__ import annotations

import multiprocessing
import multiprocessing.connection
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.telemetry import metrics as telemetry_metrics

WORKER_HOST_PREFIX = "ingress-w"


def reuseport_available() -> bool:
    """Whether the kernel offers SO_REUSEPORT load-balanced binds."""
    return hasattr(socket, "SO_REUSEPORT")


class ForwardedFeed:
    """Worker-side membership surface: the router polls ``current()``
    between batches exactly like a live
    ``resilience.discovery.MembershipFeed``; the supervisor's control
    pipe pushes ``(version, payload)`` into it. ``decode`` (settable
    by ``worker_init``) maps the forwarded payload to the member list
    the router's ``wrap=`` consumes."""

    def __init__(self, name: str):
        self.name = name
        self.decode: Callable[[Any], Any] = lambda payload: payload
        self._lock = threading.Lock()
        self._version = 0
        self._payload: Any = ()

    def _set(self, version: int, payload: Any) -> None:
        with self._lock:
            self._version = int(version)
            self._payload = payload

    def current(self):
        with self._lock:
            version, payload = self._version, self._payload
        return version, self.decode(payload)


class WorkerContext:
    """What ``worker_init`` gets inside the worker process."""

    def __init__(self, ingress, index: int, feeds: Dict[str, ForwardedFeed]):
        self.ingress = ingress
        self.index = index
        self._feeds = feeds

    def membership(self, name: str) -> ForwardedFeed:
        """The forwarded membership feed for deployment ``name`` —
        hand it to a router as ``membership=``."""
        feed = self._feeds.get(name)
        if feed is None:
            feed = self._feeds[name] = ForwardedFeed(name)
        return feed


class _MergedView:
    """Per-worker shim behind ``fleetview.install``: ``/metrics``
    serves the supervisor's latest merged bank exposition; until the
    first merge arrives, ``render_installed`` returns None and the
    route falls back to the process-local exposition."""

    def __init__(self):
        self._text: Optional[str] = None

    def merged_exposition(self) -> Optional[str]:
        return self._text


def _default_encode(members) -> Any:
    """Default membership encoder: index descriptors. Actor handles
    (and arbitrary live objects) do not survive a process boundary;
    workers that need real member identity pass their own encoder."""
    return list(range(len(members)))


def _worker_main(index: int, spec: Dict[str, Any], conn) -> None:
    """Worker process entry: build the ingress, mount policies via
    ``worker_init``, then serve control messages until stopped. Runs
    as the child's MAIN thread; the heartbeat runs beside it."""
    from ray_tpu.ingress.http import PolicyIngress
    from ray_tpu.telemetry import fleetview

    feeds: Dict[str, ForwardedFeed] = {}
    kwargs = dict(spec.get("ingress_kwargs") or {})
    listen_sock = spec.get("listen_sock")
    if listen_sock is not None:
        ingress = PolicyIngress(
            spec["host"], spec["port"],
            listen_sock=listen_sock, **kwargs,
        )
    else:
        ingress = PolicyIngress(
            spec["host"], spec["port"], reuse_port=True, **kwargs,
        )
    ctx = WorkerContext(ingress, index, feeds)
    merged = _MergedView()
    stop_hb = threading.Event()
    try:
        worker_init = spec.get("worker_init")
        if worker_init is not None:
            worker_init(ctx)
        ingress.start()
        fleetview.install(merged)

        # ray-tpu: thread=ingress-worker-hb
        def heartbeat() -> None:
            seq = 0
            host = f"{WORKER_HOST_PREFIX}{index}"
            while not stop_hb.wait(spec["heartbeat_s"]):
                snap = {
                    "host": host,
                    "seq": seq,
                    "ts": time.time(),
                    "metrics": fleetview.registry_snapshot(),
                    "spans": [],
                    "arrivals": [],
                }
                # worker_init may attach a callable as
                # ``ctx.ingress.extra_stats`` to ship custom
                # process-local numbers home (e.g. the flood bench's
                # per-worker compile counters)
                extra = getattr(ingress, "extra_stats", None)
                try:
                    extra_out = extra() if callable(extra) else None
                except Exception:
                    extra_out = None
                stats = {
                    "pid": os.getpid(),
                    "port": ingress.port,
                    "draining": ingress.draining,
                    "ingress": ingress.stats(),
                    "extra": extra_out,
                }
                try:
                    conn.send(("hb", index, snap, stats))
                except (OSError, ValueError):
                    return  # supervisor is gone; ctl loop exits too
                seq += 1

        hb = threading.Thread(
            target=heartbeat, daemon=True, name="ingress_worker_hb"
        )
        hb.start()

        def handle(msg) -> bool:
            op = msg[0]
            if op == "stop":
                return False
            elif op == "membership":
                _, name, version, payload = msg
                feed = feeds.get(name)
                if feed is None:
                    feed = feeds[name] = ForwardedFeed(name)
                feed._set(version, payload)
            elif op == "drain":
                ingress.drain(msg[1])
            elif op == "merged":
                merged._text = msg[1]
            return True

        # apply the supervisor's pre-spawn replay (membership, drain,
        # merged text) BEFORE reporting ready: once ready is visible
        # the bank is expected to route
        live = True
        while live and conn.poll(0):
            live = handle(conn.recv())
        if live:
            conn.send(("ready", index, ingress.port, os.getpid()))
        while live:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                break
            live = handle(msg)
    finally:
        stop_hb.set()
        try:
            ingress.stop()
        except Exception:
            pass
        try:
            conn.close()
        except Exception:
            pass


class _WorkerSlot:
    __slots__ = ("proc", "conn", "pid", "port", "stats", "ready")

    def __init__(self, proc, conn):
        self.proc = proc
        self.conn = conn
        self.pid: Optional[int] = None
        self.port: Optional[int] = None
        self.stats: Optional[Dict[str, Any]] = None
        self.ready = False


class IngressSupervisor:
    """Run + babysit a bank of ingress worker processes on one port.

    ``worker_init(ctx)`` runs inside EACH worker after fork (and after
    every respawn) to mount policies; see the module docstring for the
    membership-forwarding contract. ``follow_membership(name)``
    subscribes the supervisor to a controller feed and keeps every
    worker's :class:`ForwardedFeed` current.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        num_workers: int = 2,
        worker_init: Optional[Callable[[WorkerContext], None]] = None,
        ingress_kwargs: Optional[Dict[str, Any]] = None,
        respawn: bool = True,
        poll_s: float = 0.2,
        heartbeat_s: float = 0.25,
        metrics_interval_s: float = 1.0,
        notice_host: Optional[str] = None,
        notice_poll_s: float = 2.0,
        force_inherited_listener: bool = False,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.host = host
        self._requested_port = int(port)
        self.port: Optional[int] = None
        self.num_workers = int(num_workers)
        self.worker_init = worker_init
        self.ingress_kwargs = dict(ingress_kwargs or {})
        self.respawn = bool(respawn)
        self.poll_s = float(poll_s)
        self.heartbeat_s = float(heartbeat_s)
        self.metrics_interval_s = float(metrics_interval_s)
        self.notice_host = notice_host or socket.gethostname()
        self.notice_poll_s = float(notice_poll_s)
        self._use_reuseport = (
            reuseport_available() and not force_inherited_listener
        )
        self._mp = multiprocessing.get_context("fork")
        self._probe_sock: Optional[socket.socket] = None
        self._listen_sock: Optional[socket.socket] = None
        self._slots: List[Optional[_WorkerSlot]] = []
        self._feeds: Dict[str, Any] = {}
        self._feed_state: Dict[str, tuple] = {}  # name -> (ver, payload)
        self._feed_encode: Dict[str, Callable] = {}
        self._agg = None
        self._merged_text: Optional[str] = None
        self._draining = False
        self._drain_grace: Optional[float] = None
        self.respawned_total = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_merge = 0.0
        self._last_notice_probe = 0.0

    # -- controller-side membership feeds --------------------------------

    def follow_membership(
        self,
        name: str,
        feed=None,
        encode: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        """Follow deployment ``name``'s controller feed and forward
        version bumps to every worker. ``feed`` defaults to
        ``serve.membership_feed(name)``; ``encode`` maps the live
        member list to a picklable payload the workers' ``decode`` /
        router ``wrap=`` resolve (default: index descriptors)."""
        if feed is None:
            from ray_tpu.serve import serve as serve_core

            feed = serve_core.membership_feed(name)
        with self._lock:
            self._feeds[name] = feed
            self._feed_encode[name] = encode or _default_encode

    # -- lifecycle -------------------------------------------------------

    def start(self, timeout_s: float = 30.0) -> "IngressSupervisor":
        if self._thread is not None:
            return self
        from ray_tpu.telemetry.fleetview import FleetAggregator

        self._agg = FleetAggregator(kv=None, subscribe=False)
        if self._use_reuseport:
            # reserve the port with a held (never-listening) member of
            # the reuseport group; workers bind their own listeners
            probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            probe.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
            )
            probe.bind((self.host, self._requested_port))
            self._probe_sock = probe
            self.port = probe.getsockname()[1]
        else:
            # fd-inheritance fallback: ONE listener bound pre-fork,
            # every worker accepts from its queue
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((self.host, self._requested_port))
            srv.listen(128)
            self._listen_sock = srv
            self.port = srv.getsockname()[1]
        # seed feed state BEFORE the first spawn so every worker's
        # replay already carries membership — no window where a bound
        # worker accepts requests it cannot route
        self._check_feeds()
        self._slots = [None] * self.num_workers
        for i in range(self.num_workers):
            self._spawn(i)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self._service_conns(timeout=0.05)
            if all(s is not None and s.ready for s in self._slots):
                break
        else:
            self.stop()
            raise RuntimeError(
                "ingress workers failed to come up in time"
            )
        telemetry_metrics.set_ingress_workers(
            "target", self.num_workers
        )
        self._thread = threading.Thread(
            target=self._pump, daemon=True, name="ingress_supervisor",
        )
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def draining(self) -> bool:
        return self._draining

    def _spawn(self, index: int) -> None:
        parent_conn, child_conn = self._mp.Pipe(duplex=True)
        spec = {
            "host": self.host,
            "port": self.port,
            "listen_sock": self._listen_sock,
            "ingress_kwargs": self.ingress_kwargs,
            "worker_init": self.worker_init,
            "heartbeat_s": self.heartbeat_s,
        }
        proc = self._mp.Process(
            target=_worker_main,
            args=(index, spec, child_conn),
            daemon=True,
            name=f"ingress_worker_{index}",
        )
        proc.start()
        child_conn.close()  # parent's copy; child keeps its own
        slot = _WorkerSlot(proc, parent_conn)
        self._slots[index] = slot
        # replay control state so a respawned worker converges onto
        # the bank: last-known membership per feed, drain, merged text
        with self._lock:
            states = dict(self._feed_state)
            draining = self._draining
            grace = self._drain_grace
            merged = self._merged_text
        for name, (version, payload) in states.items():
            self._send(slot, ("membership", name, version, payload))
        if draining:
            self._send(slot, ("drain", grace))
        if merged is not None:
            self._send(slot, ("merged", merged))

    @staticmethod
    def _send(slot: _WorkerSlot, msg) -> bool:
        try:
            slot.conn.send(msg)
            return True
        except (OSError, ValueError, BrokenPipeError):
            return False

    def _broadcast(self, msg) -> None:
        for slot in self._slots:
            if slot is not None and slot.proc.is_alive():
                self._send(slot, msg)

    # -- the control pump -------------------------------------------------

    # ray-tpu: thread=ingress-supervisor
    def _pump(self) -> None:
        while not self._stop.is_set():
            try:
                self._service_conns(timeout=self.poll_s)
                self._check_feeds()
                self._check_notice()
                self._merge_metrics()
                self._reap_and_respawn()
            except Exception:
                # the bank must survive any one pump hiccup
                time.sleep(self.poll_s)

    def _service_conns(self, timeout: float) -> None:
        conns = {
            slot.conn: slot
            for slot in self._slots
            if slot is not None
        }
        if not conns:
            time.sleep(timeout)
            return
        try:
            ready = multiprocessing.connection.wait(
                list(conns), timeout=timeout
            )
        except OSError:
            return
        for conn in ready:
            slot = conns[conn]
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                continue  # liveness check handles the corpse
            op = msg[0]
            if op == "ready":
                _, _idx, port, pid = msg
                slot.port = port
                slot.pid = pid
                slot.ready = True
            elif op == "hb":
                _, _idx, snap, stats = msg
                slot.stats = stats
                slot.pid = stats.get("pid", slot.pid)
                if self._agg is not None:
                    self._agg.ingest(snap)

    def _check_feeds(self) -> None:
        with self._lock:
            feeds = dict(self._feeds)
        for name, feed in feeds.items():
            try:
                version, members = feed.current()
            except Exception:
                continue
            with self._lock:
                prev = self._feed_state.get(name)
                if prev is not None and prev[0] == version:
                    continue
                try:
                    payload = self._feed_encode[name](members)
                except Exception:
                    continue
                self._feed_state[name] = (version, payload)
            self._broadcast(("membership", name, version, payload))

    def _check_notice(self) -> None:
        if self._draining:
            return
        now = time.monotonic()
        if now - self._last_notice_probe < self.notice_poll_s:
            return
        self._last_notice_probe = now
        try:
            from ray_tpu.resilience import provider_notice

            grace = provider_notice.probe(self.notice_host)
        except Exception:
            grace = None
        if grace is not None:
            self.drain(grace)

    def _merge_metrics(self) -> None:
        now = time.monotonic()
        if now - self._last_merge < self.metrics_interval_s:
            return
        self._last_merge = now
        telemetry_metrics.set_ingress_workers(
            "live", self.num_live()
        )
        if self._agg is None:
            return
        try:
            text = self._agg.merged_exposition()
        except Exception:
            return
        with self._lock:
            self._merged_text = text
        self._broadcast(("merged", text))

    def _reap_and_respawn(self) -> None:
        if self._stop.is_set():
            return
        for i, slot in enumerate(self._slots):
            if slot is None or slot.proc.is_alive():
                continue
            try:
                slot.conn.close()
            except Exception:
                pass
            if not self.respawn:
                continue
            self.respawned_total += 1
            telemetry_metrics.inc_ingress_worker_respawns()
            self._spawn(i)

    # -- bank-wide operations ---------------------------------------------

    def drain(self, grace_s: Optional[float] = None) -> None:
        """Drain the WHOLE bank: every worker flips to healthz-503 +
        connection-close at once (the PR-19 provider-notice path, per
        process)."""
        with self._lock:
            self._draining = True
            self._drain_grace = grace_s
        self._broadcast(("drain", grace_s))

    def merged_metrics(self) -> Optional[str]:
        """The bank's merged Prometheus exposition (what any worker's
        ``/metrics`` serves once the first merge propagated)."""
        if self._agg is None:
            return None
        return self._agg.merged_exposition()

    def num_live(self) -> int:
        return sum(
            1
            for s in self._slots
            if s is not None and s.proc.is_alive()
        )

    def worker_pids(self) -> List[Optional[int]]:
        return [
            (s.proc.pid if s is not None else None)
            for s in self._slots
        ]

    def worker_stats(self) -> Dict[int, Optional[Dict[str, Any]]]:
        """Last heartbeat-reported stats per worker index."""
        return {
            i: (s.stats if s is not None else None)
            for i, s in enumerate(self._slots)
        }

    def stats(self) -> Dict[str, Any]:
        return {
            "url": self.url if self.port else None,
            "num_workers": self.num_workers,
            "num_live": self.num_live(),
            "respawned_total": self.respawned_total,
            "draining": self._draining,
            "reuseport": self._use_reuseport,
            "feeds": sorted(self._feeds),
        }

    def stop(self, join_timeout: float = 10.0) -> None:
        self._stop.set()
        self._broadcast(("stop",))
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=join_timeout)
        self._thread = None
        deadline = time.monotonic() + join_timeout
        for slot in self._slots:
            if slot is None:
                continue
            slot.proc.join(
                timeout=max(0.1, deadline - time.monotonic())
            )
            if slot.proc.is_alive():
                slot.proc.terminate()
                slot.proc.join(timeout=2.0)
            if slot.proc.is_alive():
                slot.proc.kill()
            try:
                slot.conn.close()
            except Exception:
                pass
        self._slots = []
        for sockobj in (self._probe_sock, self._listen_sock):
            if sockobj is not None:
                try:
                    sockobj.close()
                except OSError:
                    pass
        self._probe_sock = None
        self._listen_sock = None
